"""Conservation-ledger accounting plane (ISSUE 15).

Unit semantics (stations, equations, pending entries, the owner
cardinality cap), the relay/engine wiring driven by REAL HTTP traffic,
the deliberately mis-wired-route negative test (the audit must catch a
route that forgets to count), the scheduler poison-retry
no-double-count pin, the write-behind queued==drained balance, the
recompile/bandwidth sentinels, and the GET /ledger read surface.
"""

import json
import re
import urllib.error
import urllib.request

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import ledger as ledger_mod
from evolu_tpu.obs import metrics
from evolu_tpu.obs.ledger import Ledger
from evolu_tpu.server.relay import RelayServer, RelayStore, ShardedRelayStore
from evolu_tpu.sync import protocol

BASE = 1700000000000


def setup_function(_fn):
    ledger_mod.reset()
    ledger_mod.set_enabled(True)


def _ts(i, node="89e3b4f11a2c5d70"):
    return timestamp_to_string(Timestamp(BASE + i * 1000, 0, node))


def _sync_req(user, node, n_msgs, start=0, ts_list=None):
    msgs = tuple(
        protocol.EncryptedCrdtMessage(t, b"ct-%d" % i)
        for i, t in enumerate(
            ts_list
            if ts_list is not None
            else [_ts(start + i, node) for i in range(n_msgs)]
        )
    )
    return protocol.SyncRequest(msgs, user, node, "{}")


def _post(url, req, expect_error=None):
    body = protocol.encode_sync_request(req)
    try:
        r = urllib.request.urlopen(
            urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/octet-stream"},
            ),
            timeout=30,
        )
        return protocol.decode_sync_response(r.read())
    except urllib.error.HTTPError as e:
        if expect_error is not None and e.code == expect_error:
            return None
        raise


def _get_json(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read().decode("utf-8"))


# --- unit semantics ---


def test_counts_totals_and_owner_subledgers():
    led = Ledger()
    led.count(ledger_mod.INGRESS_SYNC, 5, owner="alice")
    led.count(ledger_mod.INGRESS_SYNC, 2, owner="bob")
    led.count(ledger_mod.STORE_INSERTED, 7)
    assert led.total(ledger_mod.INGRESS_SYNC) == 7
    assert led.owner_totals("alice") == {ledger_mod.INGRESS_SYNC: 5}
    assert led.audit() == []  # 7 in, 7 out
    led.count(ledger_mod.STORE_DUPLICATE, 1)
    v = led.audit()
    assert len(v) == 1 and v[0]["equation"] == "server-flow"
    assert v[0]["delta"] == -1
    assert v[0]["rhs"][ledger_mod.STORE_DUPLICATE] == 1


def test_audit_reports_per_station_deltas_and_barrier_scoping():
    led = Ledger()
    led.count(ledger_mod.WB_QUEUED, 10)
    # Mid-stream: the wb balance only holds at a drain barrier.
    assert led.audit(at_barrier=False) == []
    v = led.audit(at_barrier=True)
    names = {x["equation"] for x in v}
    assert "write-behind-balance" in names
    led.count(ledger_mod.WB_DRAINED, 10)
    led.count(ledger_mod.INGRESS_SYNC, 10)
    led.count(ledger_mod.STORE_INSERTED, 10)
    assert led.audit(at_barrier=True) == []


def test_apply_plane_equations():
    led = Ledger()
    led.count(ledger_mod.APPLY_INGRESS, 10)
    led.count(ledger_mod.ROUTE_PACKED, 6)
    led.count(ledger_mod.ROUTE_OBJECT, 4)
    led.count(ledger_mod.APPLY_INSERTED, 5)
    led.count(ledger_mod.APPLY_LOSING, 2)
    led.count(ledger_mod.APPLY_DUPLICATE, 3)
    assert led.audit() == []
    led.count(ledger_mod.APPLY_INGRESS, 1)  # unrouted message
    assert [v["equation"] for v in led.audit()] == ["apply-routing"]


def test_pending_entry_commit_abort_and_single_shot():
    led = Ledger()
    e = led.pending()
    e.count(ledger_mod.INGRESS_SYNC, 3, owner="o")
    assert led.total(ledger_mod.INGRESS_SYNC) == 0  # not yet posted
    e.commit()
    e.commit()  # idempotent
    assert led.total(ledger_mod.INGRESS_SYNC) == 3
    a = led.pending()
    a.count(ledger_mod.INGRESS_SYNC, 99)
    a.abort()
    a.commit()  # after abort: nothing
    assert led.total(ledger_mod.INGRESS_SYNC) == 3


def test_owner_cardinality_cap_folds_into_overflow():
    led = Ledger(owner_cardinality_cap=4)
    for i in range(10):
        led.count(ledger_mod.INGRESS_SYNC, 1, owner=f"owner-{i}")
    owners = led.owners()
    assert len(owners) == 5  # 4 real + __overflow__
    assert led.owner_totals(ledger_mod.OWNER_OVERFLOW) == {
        ledger_mod.INGRESS_SYNC: 6
    }
    # The GLOBAL station total is never lost to the fold.
    assert led.total(ledger_mod.INGRESS_SYNC) == 10


def test_snapshot_shape_and_reset():
    led = Ledger()
    led.count(ledger_mod.INGRESS_SYNC, 2, owner="a")
    snap = led.snapshot()
    assert snap["stations"][ledger_mod.INGRESS_SYNC] == 2
    assert snap["owners"]["a"][ledger_mod.INGRESS_SYNC] == 2
    assert {e["name"] for e in snap["equations"]} >= {
        "server-flow", "write-behind-balance", "apply-routing",
        "apply-outcomes",
    }
    led.reset()
    assert led.totals() == {}
    assert led.owners() == []
    # Equations persist across reset (configuration, not data).
    led.count(ledger_mod.INGRESS_SYNC, 1)
    assert led.audit(at_barrier=True) != []


def test_disabled_ledger_records_nothing():
    led = Ledger()
    led.enabled = False
    led.count(ledger_mod.INGRESS_SYNC, 5)
    e = led.pending()
    e.count(ledger_mod.STORE_INSERTED, 5)
    e.commit()
    assert led.totals() == {}


# --- relay wiring, driven by real HTTP traffic ---


def test_per_request_relay_conserves_and_classifies():
    server = RelayServer(ShardedRelayStore(shards=2)).start()
    try:
        _post(server.url, _sync_req("alice", "a" * 16, 3))
        _post(server.url, _sync_req("alice", "a" * 16, 3))  # exact redelivery
        _post(server.url, _sync_req("bob", "b" * 16, 2, start=50))
        _post(server.url, _sync_req("carol", "c" * 16, 0))  # pull-only
        t = ledger_mod.totals()
        assert t[ledger_mod.INGRESS_SYNC] == 8
        assert t[ledger_mod.STORE_INSERTED] == 5
        assert t[ledger_mod.STORE_DUPLICATE] == 3
        assert ledger_mod.audit() == [], ledger_mod.audit()
        # Owner sub-ledgers track the same flows.
        assert ledger_mod.ledger.owner_totals("alice") == {
            ledger_mod.INGRESS_SYNC: 6,
            ledger_mod.STORE_INSERTED: 3,
            ledger_mod.STORE_DUPLICATE: 3,
        }
    finally:
        server.stop()


def test_batching_relay_conserves_across_engine_pass():
    server = RelayServer(ShardedRelayStore(shards=2), batching=True).start()
    try:
        _post(server.url, _sync_req("alice", "a" * 16, 4))
        _post(server.url, _sync_req("bob", "b" * 16, 3, start=50))
        _post(server.url, _sync_req("alice", "a" * 16, 4))  # redelivery
        t = ledger_mod.totals()
        assert t[ledger_mod.INGRESS_SYNC] == 11
        assert t[ledger_mod.STORE_INSERTED] == 7
        assert t[ledger_mod.STORE_DUPLICATE] == 4
        assert ledger_mod.audit() == [], ledger_mod.audit()
    finally:
        server.stop()


def test_non_canonical_batch_routes_singleton_and_conserves():
    server = RelayServer(RelayStore(), batching=True).start()
    try:
        # A non-canonical-width timestamp (45 chars, 3-digit counter):
        # the scheduler must dispatch the request as a singleton (never
        # a packed batch), the bounce tally must record it, and the
        # singleton path's host-oracle error surface (500 — the
        # transaction rolls the whole request back) must classify every
        # message as reject.invalid: conservation holds on the error
        # path too.
        req = _sync_req("nc-owner", "d" * 16, 0,
                        ts_list=[_ts(1, "d" * 16),
                                 "1970-01-01T00:00:00.001Z-001-deadbeefdeadbeef"])
        assert _post(server.url, req, expect_error=500) is None
        assert ledger_mod.ledger.total(ledger_mod.BOUNCE_NON_CANONICAL) == 2
        t = ledger_mod.totals()
        assert t[ledger_mod.INGRESS_SYNC] == 2
        assert t[ledger_mod.REJECT_INVALID] == 2
        assert t.get(ledger_mod.STORE_INSERTED, 0) == 0
        assert ledger_mod.audit() == [], ledger_mod.audit()
    finally:
        server.stop()


def test_scheduler_poison_retry_does_not_double_count(monkeypatch):
    from evolu_tpu.server.engine import BatchReconciler

    orig = BatchReconciler.run_batch_wire
    state = {"fails": 0}

    def flaky(self, requests):
        if state["fails"] == 0:
            state["fails"] += 1
            raise RuntimeError("injected poison")
        return orig(self, requests)

    monkeypatch.setattr(BatchReconciler, "run_batch_wire", flaky)
    server = RelayServer(RelayStore(), batching=True).start()
    try:
        _post(server.url, _sync_req("alice", "a" * 16, 3))
        assert state["fails"] == 1, "injected poison never fired"
        assert metrics.get_counter("evolu_sched_poisoned_batches_total") >= 1
        t = ledger_mod.totals()
        # Exactly once despite the failed engine pass + singleton retry.
        assert t[ledger_mod.INGRESS_SYNC] == 3
        assert t[ledger_mod.STORE_INSERTED] == 3
        assert t.get(ledger_mod.STORE_DUPLICATE, 0) == 0
        assert ledger_mod.audit() == [], ledger_mod.audit()
    finally:
        server.stop()


def test_backpressure_shed_is_a_terminal():
    from evolu_tpu.server.scheduler import SyncScheduler

    store = RelayStore()
    sched = SyncScheduler(store, max_queue=0)  # every submit sheds
    server = RelayServer(store, scheduler=sched).start()
    try:
        assert _post(server.url, _sync_req("alice", "a" * 16, 4),
                     expect_error=503) is None
        t = ledger_mod.totals()
        assert t[ledger_mod.INGRESS_SYNC] == 4
        assert t[ledger_mod.SHED_BACKPRESSURE] == 4
        assert ledger_mod.audit() == [], ledger_mod.audit()
    finally:
        server.stop()


def test_relay_500_is_a_reject_terminal(monkeypatch):
    store = RelayStore()

    def boom(request):
        raise RuntimeError("injected serve failure")

    server = RelayServer(store).start()
    monkeypatch.setattr(store, "sync_wire", boom)
    monkeypatch.setattr(store, "sync", boom)
    try:
        assert _post(server.url, _sync_req("alice", "a" * 16, 2),
                     expect_error=500) is None
        t = ledger_mod.totals()
        assert t[ledger_mod.INGRESS_SYNC] == 2
        assert t[ledger_mod.REJECT_INVALID] == 2
        assert ledger_mod.audit() == [], ledger_mod.audit()
    finally:
        server.stop()


def test_commit_then_raise_serve_posts_single_terminal():
    """Review regression: a serve that COMMITS add_messages and then
    fails before answering (here: a garbage client merkle-tree string
    parsed after the insert) must post exactly ONE terminal — the 500's
    reject.invalid — not store terminals AND a reject. The serve scope
    aborts the store classification on the error path."""
    server = RelayServer(RelayStore()).start()
    try:
        req = protocol.SyncRequest(
            (protocol.EncryptedCrdtMessage(_ts(0, "a" * 16), b"ct"),),
            "ctr-owner", "a" * 16, "not-a-merkle-tree",
        )
        assert _post(server.url, req, expect_error=500) is None
        t = ledger_mod.totals()
        assert t[ledger_mod.INGRESS_SYNC] == 1
        assert t[ledger_mod.REJECT_INVALID] == 1
        assert t.get(ledger_mod.STORE_INSERTED, 0) == 0
        assert ledger_mod.audit() == [], ledger_mod.audit()
        # The retry (valid tree) classifies the committed row once.
        _post(server.url, _sync_req("ctr-owner", "a" * 16, 1))
        t = ledger_mod.totals()
        assert t[ledger_mod.STORE_DUPLICATE] == 1
        assert ledger_mod.audit() == [], ledger_mod.audit()
    finally:
        server.stop()


def test_non_canonical_store_fallback_classifies_once():
    """Review regression: a malformed STORED timestamp makes sync_wire
    bounce to the object path, which re-runs add_messages idempotently
    — the serve scope's first-wins latch must keep the classification
    at exactly one set of terminals per request."""
    store = RelayStore()
    server = RelayServer(store).start()
    try:
        _post(server.url, _sync_req("fb-owner", "a" * 16, 2))
        # Poison the owner's stored history with a non-canonical width
        # row so the C response reader raises NonCanonicalStoreError.
        store.db.run(
            'INSERT INTO "message" ("timestamp", "userId", "content") '
            "VALUES (?, ?, ?)",
            ("1970-01-01T00:00:00.009Z-001-aaaaaaaaaaaaaaaa", "fb-owner",
             b"bad"),
        )
        base = ledger_mod.totals()
        # A diverging request (client tree "{}") must read stored rows:
        # the wire path bounces, the object path serves.
        _post(server.url, _sync_req("fb-owner", "b" * 16, 1, start=90))
        t = ledger_mod.totals()
        new_terms = (
            t.get(ledger_mod.STORE_INSERTED, 0)
            + t.get(ledger_mod.STORE_DUPLICATE, 0)
            - base.get(ledger_mod.STORE_INSERTED, 0)
            - base.get(ledger_mod.STORE_DUPLICATE, 0)
        )
        assert new_terms == 1, f"fallback double-classified: {new_terms}"
        assert ledger_mod.audit() == [], ledger_mod.audit()
    finally:
        server.stop()


def test_miswired_route_is_caught_by_the_audit(monkeypatch):
    """THE negative test: silence one route's terminal counting (the
    object store path) and the conservation audit must name the broken
    equation with a positive ingress-side delta — a ledger that cannot
    catch a mis-wired route is worse than none."""
    from evolu_tpu.server import relay as relay_mod

    monkeypatch.setattr(relay_mod, "_ledger_store_apply",
                        lambda *_a, **_kw: None)
    server = RelayServer(RelayStore()).start()
    try:
        _post(server.url, _sync_req("alice", "a" * 16, 3))
        violations = ledger_mod.audit()
        assert violations, "audit missed the silenced store route"
        v = violations[0]
        assert v["equation"] == "server-flow"
        assert v["delta"] == 3  # 3 ingressed, 0 reached a terminal
        assert v["lhs"][ledger_mod.INGRESS_SYNC] == 3
    finally:
        server.stop()


# --- write-behind: the queued == drained balance ---


def test_write_behind_queue_balances_at_drain_barrier(tmp_path):
    server = RelayServer(
        ShardedRelayStore(str(tmp_path / "wb.db"), shards=2),
        write_behind=True,
        write_behind_log=str(tmp_path / "wb.wblog"),
    ).start()
    try:
        _post(server.url, _sync_req("alice", "a" * 16, 5))
        _post(server.url, _sync_req("bob", "b" * 16, 3, start=50))
        _post(server.url, _sync_req("alice", "a" * 16, 5))  # redelivery
        server.write_behind.flush()
        t = ledger_mod.totals()
        assert t[ledger_mod.WB_QUEUED] == t[ledger_mod.WB_DRAINED]
        assert t[ledger_mod.INGRESS_SYNC] == 13
        assert (t[ledger_mod.STORE_INSERTED]
                + t[ledger_mod.STORE_DUPLICATE]) == 13
        assert t[ledger_mod.STORE_INSERTED] == 8
        assert ledger_mod.audit(at_barrier=True) == [], ledger_mod.audit()
        # GET /ledger runs the audit under the drain barrier itself.
        payload = _get_json(server.url + "/ledger")
        assert payload["violations"] == []
        assert payload["stations"][ledger_mod.WB_QUEUED] == 13
    finally:
        server.stop()


# --- GET /ledger + /stats section ---


def test_ledger_endpoint_and_stats_section():
    server = RelayServer(RelayStore()).start()
    try:
        _post(server.url, _sync_req("alice", "a" * 16, 2))
        payload = _get_json(server.url + "/ledger")
        assert payload["stations"][ledger_mod.INGRESS_SYNC] == 2
        assert payload["owners"]["alice"][ledger_mod.STORE_INSERTED] == 2
        assert payload["violations"] == []
        assert any(e["name"] == "server-flow" for e in payload["equations"])
        stats = _get_json(server.url + "/stats")
        assert stats["ledger"]["stations"][ledger_mod.INGRESS_SYNC] == 2
        assert stats["ledger"]["violations"] == []
    finally:
        server.stop()


# --- apply plane, driven through the real client worker ---


def test_client_apply_plane_conserves():
    from evolu_tpu.runtime.client import create_evolu

    evolu = create_evolu({"todo": ("title", "isCompleted")})
    try:
        for i in range(5):
            evolu.create("todo", {"title": f"t{i}", "isCompleted": False})
        evolu.worker.flush()
        t = ledger_mod.totals()
        assert t[ledger_mod.APPLY_INGRESS] >= 10  # 2 cols x 5 rows
        routed = (t.get(ledger_mod.ROUTE_PACKED, 0)
                  + t.get(ledger_mod.ROUTE_OBJECT, 0)
                  + t.get(ledger_mod.ROUTE_SEQUENTIAL, 0))
        assert routed == t[ledger_mod.APPLY_INGRESS]
        assert ledger_mod.audit() == [], ledger_mod.audit()
    finally:
        evolu.dispose()


def test_apply_rollback_counts_rejected():
    from evolu_tpu.core.types import CrdtMessage, TableDefinition
    from evolu_tpu.storage import (
        apply_messages, init_db_model, open_database, update_db_schema,
    )

    db = open_database()
    init_db_model(db, "legal winner thank year wave sausage worth useful "
                      "legal winner thank yellow")
    update_db_schema(db, [TableDefinition.of("todo", ["title"])])
    bad = [CrdtMessage(_ts(1), "todo", "r1", "title", "x"),
           CrdtMessage("not-a-timestamp", "todo", "r1", "title", "y")]
    with pytest.raises(Exception):
        apply_messages(db, {}, bad)
    t = ledger_mod.totals()
    assert t[ledger_mod.APPLY_INGRESS] == 2
    assert t[ledger_mod.APPLY_REJECTED] == 2
    assert ledger_mod.audit() == [], ledger_mod.audit()


# --- recompile sentinel (satellite) ---


def test_recompile_sentinel_flat_within_buckets():
    from evolu_tpu.server import engine as eng_mod

    server = RelayServer(ShardedRelayStore(shards=2), batching=True).start()
    try:
        _post(server.url, _sync_req("alice", "a" * 16, 8))  # warm-up
        assert metrics.get_gauge("evolu_jit_cache_size", cache="merkle") == (
            eng_mod.merkle_jit_cache_size()
        )
        recompiles = metrics.get_counter(
            "evolu_jit_recompiles_total", cache="merkle"
        )
        # Same bucket (8 and 5 rows both pad to the 64-row bucket):
        # the counter must stay flat.
        _post(server.url, _sync_req("bob", "b" * 16, 5, start=100))
        _post(server.url, _sync_req("carol", "c" * 16, 8, start=200))
        assert metrics.get_counter(
            "evolu_jit_recompiles_total", cache="merkle"
        ) == recompiles, "recompile sentinel moved within one bucket"
    finally:
        server.stop()


def test_recompile_sentinel_counts_growth_and_flight_event():
    from evolu_tpu.obs import flight
    from evolu_tpu.server import engine as eng_mod

    eng_mod._JIT_SENTINEL_SIZES.clear()
    before = metrics.get_counter("evolu_jit_recompiles_total", cache="merkle")
    eng_mod.observe_jit_caches(0)  # baseline observation
    real = eng_mod.merkle_jit_cache_size()
    # Simulate growth without compiling anything: shrink the recorded
    # baseline so the next diff is positive.
    eng_mod._JIT_SENTINEL_SIZES["merkle"] = real - 2 if real >= 2 else 0
    flight.clear()
    eng_mod.observe_jit_caches(batch_rows=777)
    grown = metrics.get_counter("evolu_jit_recompiles_total", cache="merkle")
    assert grown >= before + (2 if real >= 2 else real)
    if real:
        evs = [e for e in flight.dump() if e.target == "kernel:jit"]
        assert evs and evs[-1].fields["bucket_rows"] >= 777
    eng_mod._JIT_SENTINEL_SIZES.clear()


# --- tunnel-bandwidth plane (satellite) ---


def test_pull_instrumentation_counts_waves():
    import numpy as np

    import jax

    from evolu_tpu.ops import to_host_many

    before = metrics.get_counter("evolu_pull_bytes_total")
    arrs = to_host_many(jax.numpy.arange(1024, dtype=jax.numpy.int32),
                        np.arange(256, dtype=np.int64))
    wave = sum(a.nbytes for a in arrs)
    assert metrics.get_counter("evolu_pull_bytes_total") == before + wave
    got = metrics.registry.get_histogram("evolu_pull_wave_bytes")
    assert got is not None and got[3] >= 1
    assert metrics.get_counter("evolu_pull_seconds_total") > 0


# --- evidence dump carries the ledger ---


def test_write_evidence_includes_ledger_snapshot(tmp_path):
    from evolu_tpu.obs import trace

    ledger_mod.count(ledger_mod.INGRESS_SYNC, 4, owner="ev-owner")
    path = trace.write_evidence("ledger-evidence-test", seed=1)
    assert not path.startswith("<")
    payload = json.loads(open(path).read())
    assert payload["ledger"]["stations"][ledger_mod.INGRESS_SYNC] == 4
    assert "violations" in payload["ledger"]
