"""Logging/tracing subsystem (reference log.ts + SURVEY.md §5)."""

from evolu_tpu.core.types import CrdtClock
from evolu_tpu.storage.clock import read_clock, update_clock
from evolu_tpu.storage.schema import init_db_model
from evolu_tpu.storage.sqlite import PySqliteDatabase
from evolu_tpu.utils.log import Logger, logger


def test_target_gating():
    lg = Logger(enabled=False)
    lg.log("dev", "hidden")
    assert lg.recent_events() == []
    lg.configure("dev")
    lg.log("dev", "shown")
    lg.log("clock:read", "not this target")
    assert [e.message for e in lg.recent_events()] == ["shown"]
    lg.configure(True)
    lg.log("clock:read", "now everything")
    assert len(lg.recent_events()) == 2


def test_span_records_duration_even_when_disabled():
    lg = Logger(enabled=False)
    with lg.span("kernel:merge", "plan", n=3):
        pass
    stats = lg.duration_stats("kernel:merge")
    assert stats is not None and stats[0] == 1 and stats[1] >= 0
    (ev,) = lg.recent_events("kernel:merge")
    assert ev.duration_ms is not None and ev.fields == {"n": 3}


def test_clock_targets_fire(capsys):
    logger.configure(["clock:read", "clock:update"])
    try:
        db = PySqliteDatabase()
        init_db_model(db, mnemonic=None)
        clock = read_clock(db)
        update_clock(db, CrdtClock(clock.timestamp, clock.merkle_tree))
        out = capsys.readouterr().out
        assert "[clock:read]" in out and "[clock:update]" in out
        targets = [e.target for e in logger.recent_events()]
        assert "clock:read" in targets and "clock:update" in targets
    finally:
        logger.configure(False)
        logger.clear()


def test_ring_is_bounded():
    lg = Logger(enabled=True, capacity=4)
    for i in range(10):
        lg.log("dev", str(i))
    msgs = [e.message for e in lg.recent_events()]
    assert msgs == ["6", "7", "8", "9"]


def test_span_trace_annotations_fire_under_the_target_name():
    """With trace annotations enabled (VERDICT #7), every span opens a
    jax.profiler.TraceAnnotation named by the SAME target the
    log/metrics surfaces use; disabled spans touch nothing."""
    import evolu_tpu.utils.log as log_mod

    entered = []

    class FakeAnnotation:
        def __init__(self, name):
            self.name = name

        def __enter__(self):
            entered.append(("enter", self.name))
            return self

        def __exit__(self, *exc):
            entered.append(("exit", self.name))

    lg = Logger()
    orig = log_mod._trace_annotation_cls
    try:
        log_mod._trace_annotation_cls = FakeAnnotation
        with lg.span("kernel:merkle", "reconcile_ingest", n=3):
            pass
        with lg.span("kernel:merge"):
            pass
    finally:
        log_mod._trace_annotation_cls = orig
    assert entered == [
        ("enter", "kernel:merkle|reconcile_ingest"),
        ("exit", "kernel:merkle|reconcile_ingest"),
        ("enter", "kernel:merge"),
        ("exit", "kernel:merge"),
    ]
    # Disabled (the default): spans never construct an annotation.
    entered.clear()
    with lg.span("kernel:merge"):
        pass
    assert entered == []


def test_enable_trace_annotations_real_jax_class():
    """The real jax.profiler.TraceAnnotation binds and runs (smoke —
    actual trace capture is benchmarks/kernel_trace.py)."""
    from evolu_tpu.utils.log import enable_trace_annotations
    import evolu_tpu.utils.log as log_mod

    try:
        enable_trace_annotations(True)
        assert log_mod._trace_annotation_cls is not None
        with Logger().span("kernel:merge", "smoke"):
            pass
    finally:
        enable_trace_annotations(False)
    assert log_mod._trace_annotation_cls is None
