"""Merkle trie golden tests.

Expected values ported from the reference's vitest snapshots
(packages/evolu/test/merkleTree.test.ts +
__snapshots__/merkleTree.test.ts.snap). Hashes are JS signed int32
(XOR coercion), serialization matches JS JSON.stringify property order.
"""

import json
import random

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    create_initial_merkle_tree,
    diff_merkle_trees,
    insert_into_merkle_tree,
    key_to_timestamp_millis,
    merkle_tree_from_string,
    merkle_tree_to_string,
    minutes_base3,
)
from evolu_tpu.core.timestamp import timestamp_to_hash
from evolu_tpu.core.murmur import to_int32
from evolu_tpu.core.types import Timestamp


def node1(millis=0, counter=0):
    return Timestamp(millis, counter, "0000000000000001")


def test_create_initial_merkle_tree():
    assert create_initial_merkle_tree() == {}


def test_insert_single_at_epoch():
    # snapshot `insertIntoMerkleTree 1`
    tree = insert_into_merkle_tree(node1(), {})
    assert tree == {"hash": -1416139081, "0": {"hash": -1416139081}}


def test_insert_single_2022():
    # snapshot `insertIntoMerkleTree 2` — ts 1656873738591, 16-digit base-3 key
    tree = insert_into_merkle_tree(node1(1656873738591), {})
    assert tree["hash"] == -468843282
    key = minutes_base3(1656873738591)
    # Path read off the snapshot's nesting: 1→2→2→0→2→2→1→2→2→2→0→0→1→1→2→0
    assert key == "1220221222001120"
    node = tree
    for c in key:
        node = node[c]
        assert node["hash"] == -468843282
    assert "0" not in node and "1" not in node and "2" not in node


def test_insert_both_and_order_independence():
    # snapshot `insertIntoMerkleTree 3` — root hash is XOR of both
    ts1, ts2 = node1(), node1(1656873738591)
    t_a = insert_into_merkle_tree(ts2, insert_into_merkle_tree(ts1, {}))
    t_b = insert_into_merkle_tree(ts1, insert_into_merkle_tree(ts2, {}))
    assert t_a == t_b
    assert t_a["hash"] == 1335454297
    assert t_a["0"]["hash"] == -1416139081


def test_diff_merkle_trees():
    assert diff_merkle_trees({}, {}) is None
    mt = insert_into_merkle_tree(node1(1656873738591), {})
    # snapshot `diffMerkleTrees 2` — minute floor of the inserted ts
    assert diff_merkle_trees({}, mt) == 1656873720000
    assert diff_merkle_trees({}, mt) == diff_merkle_trees(mt, {})


def test_diff_detects_divergence_minute():
    # Modern millis ⇒ full 16-digit keys ⇒ diff pinpoints the exact minute.
    # (Tiny millis produce short, right-padded keys — a reference quirk we
    # reproduce: see keyToTimestamp right-padding, merkleTree.ts:55-61.)
    t0 = 1656873720000  # minute-aligned
    base = {}
    for m in [t0, t0 + 60000, t0 + 120000, t0 + 600000]:
        base = insert_into_merkle_tree(node1(m), base)
    other = insert_into_merkle_tree(node1(t0 + 120000, 1), base)
    assert diff_merkle_trees(base, other) == t0 + 120000


def test_key_to_timestamp_millis():
    assert key_to_timestamp_millis("") == 0
    assert key_to_timestamp_millis(minutes_base3(1656873720000)) == 1656873720000


def test_serialization_matches_js_json():
    tree = insert_into_merkle_tree(
        node1(), insert_into_merkle_tree(node1(1656873738591), {})
    )
    s = merkle_tree_to_string(tree)
    # JS property order: numeric keys ascending first, then "hash".
    assert s.startswith('{"0":{"hash":-1416139081}')
    assert merkle_tree_from_string(s) == tree
    # No whitespace (JSON.stringify default).
    assert " " not in s


def test_hash_zero_vs_missing_distinct():
    # undefined !== 0 in the diff walk.
    t1 = {"hash": 0, "0": {"hash": 0}}
    t2 = {}
    assert diff_merkle_trees(t1, t2) is not None


def test_apply_prefix_xors_equivalence():
    rng = random.Random(42)
    timestamps = [
        Timestamp(rng.randrange(0, 2**41), rng.randrange(0, 65536), "0000000000000001")
        for _ in range(200)
    ]
    seq = {}
    for t in timestamps:
        seq = insert_into_merkle_tree(t, seq)

    # Batch: aggregate XOR per full 16-level prefix chain, like the TPU path.
    deltas = {}
    for t in timestamps:
        key = minutes_base3(t.millis)
        h = timestamp_to_hash(t)
        deltas[key] = to_int32(deltas.get(key, 0) ^ h)
    batched = apply_prefix_xors({}, deltas)
    assert batched == seq
