"""Mesh-sharded engine (ISSUE 12, parallel/mesh.py::MeshContext):
one shard_map pass reconciles every owner across the device mesh with
STABLE owner→device placement. Gates: sharded `run_batch_wire`
responses + SQLite end state byte-identical to the SINGLE-DEVICE
engine; jit caches flat across varying batch sizes within a bucket
(the fused-seed recompile trap); the mesh-sharded winner cache plans
identically to the single-device cache and holds slot == SQLite
MAX(timestamp) per shard; the `evolu_mesh_*` obs family and the relay
`/stats` mesh section are live; the sharded path is config-selectable
and DEFAULT-OFF."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.obs import metrics
from evolu_tpu.parallel.mesh import MeshContext, create_mesh, owner_shard
from evolu_tpu.server.relay import RelayServer, ShardedRelayStore
from evolu_tpu.sync import protocol

BASE = 1_700_000_000_000


def _msgs(node: str, start: int, n: int, step_ms: int = 1000):
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (start + i) * step_ms, 0, node)),
            b"ct-%d" % (start + i),
        )
        for i in range(n)
    )


from tests.conftest import relay_store_dump as _store_dump  # noqa: E402


def _request_rounds(owners: int, rounds: int):
    """Deterministic multi-round traffic: per round, every owner pushes
    a partially-overlapping window (duplicates exercise the was-new
    correction) and pulls against an empty client tree (a non-trivial
    diff response that streams stored messages)."""
    out = []
    for rnd in range(rounds):
        reqs = []
        for i in range(owners):
            node = f"{i + 1:016x}"
            reqs.append(protocol.SyncRequest(
                _msgs(node, rnd * 4, 6 + (i % 5)), f"mesh-u{i:03d}", node, "{}"
            ))
        out.append(tuple(reqs))
    return out


def test_sharded_run_batch_wire_byte_identical_to_single_device_engine():
    """THE parity gate: the 8-device sharded pass must serve the exact
    bytes — and commit the exact SQLite end state — of a single-device
    engine, round after round (overlapping pushes included)."""
    from evolu_tpu.server.engine import BatchReconciler

    sharded_store = ShardedRelayStore(shards=4)
    single_store = ShardedRelayStore(shards=4)
    eng = BatchReconciler(sharded_store, mesh_ctx=MeshContext())
    oracle = BatchReconciler(single_store, mesh=create_mesh(1))
    assert eng.mesh.devices.size >= 8, "conftest must supply the 8-device mesh"
    try:
        for reqs in _request_rounds(owners=13, rounds=3):
            assert eng.run_batch_wire(reqs) == oracle.run_batch_wire(reqs)
        assert _store_dump(sharded_store) == _store_dump(single_store)
    finally:
        eng.close()
        oracle.close()
        sharded_store.close()
        single_store.close()


def test_stable_placement_is_stable_and_owner_sharded():
    """Placement is a pure function (same owner → same device across
    contexts and batches) and hot-owner chunks spill round-robin from
    the owner's home shard."""
    ctx = MeshContext()
    assert ctx.n_shards >= 8
    for o in ("alice", "bob", "user-123"):
        assert ctx.place(o) == owner_shard(o, ctx.n_shards) == MeshContext().place(o)
    shards = ctx.assign_stable({("hot", 0): 10, ("hot", 1): 10, ("cold", 0): 1})
    home = ctx.place("hot")
    assert ("hot", 0) in shards[home]
    assert ("hot", 1) in shards[(home + 1) % ctx.n_shards]
    assert ("cold", 0) in shards[ctx.place("cold")]


def test_sharded_engine_jit_cache_flat_within_bucket():
    """The recompile fence for the sharded pipeline (satellite 2):
    varying batch sizes inside one power-of-two row bucket must not
    add jit-cache entries (the fused-seed negative-result trap —
    docs/BENCHMARKS.md)."""
    from evolu_tpu.server import engine as eng_mod
    from evolu_tpu.server.engine import BatchReconciler

    store = ShardedRelayStore(shards=2)
    eng = BatchReconciler(store, mesh_ctx=MeshContext())
    try:
        # Warm-up compiles the sharded kernels for the smallest bucket.
        eng.run_batch_wire([protocol.SyncRequest(
            _msgs("a" * 16, 0, 3), "jit-warm", "a" * 16, "{}")])
        size0 = eng_mod.merkle_jit_cache_size()
        assert size0 > 0, "warm-up must have compiled the Merkle kernel"
        for i, n in enumerate((1, 2, 4, 6)):  # all inside the 64-row bucket
            eng.run_batch_wire([protocol.SyncRequest(
                _msgs(f"{i + 0x70:016x}", 0, n), f"jit-m{i}",
                f"{i + 0x70:016x}", "{}")])
        assert eng_mod.merkle_jit_cache_size() == size0, (
            "a varying micro-batch size recompiled the sharded pipeline"
        )
    finally:
        eng.close()
        store.close()


def test_reconcile_owner_batches_stable_placement_parity():
    """The client/pod multi-owner reconcile under stable placement must
    produce the same per-owner plans, deltas, and digest as the LPT
    layout (the decoders are layout-agnostic — pinned here)."""
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.parallel.reconcile import reconcile_owner_batches

    mesh = create_mesh()
    batches = {}
    for o in range(10):
        node = f"{o + 1:016x}"
        batches[f"own{o}"] = [
            CrdtMessage(
                timestamp_to_string(Timestamp(BASE + i * 1000, 0, node)),
                "todo", f"r{i % 3}", "title", f"v{o}-{i}",
            )
            for i in range(5 + o)
        ]
    lpt, digest_lpt = reconcile_owner_batches(mesh, batches, {})
    stable, digest_stable = reconcile_owner_batches(
        mesh, batches, {}, mesh_ctx=MeshContext(mesh)
    )
    assert digest_lpt == digest_stable
    assert lpt.keys() == stable.keys()
    for o in lpt:
        assert lpt[o][0] == stable[o][0]  # xor masks
        assert lpt[o][1] == stable[o][1]  # upserts
        assert lpt[o][2] == stable[o][2]  # minute deltas


# -- the mesh-sharded winner cache --


def _client_db():
    from evolu_tpu.storage.native import open_database
    from evolu_tpu.storage.schema import init_db_model

    db = open_database(":memory:", "auto")
    init_db_model(db, mnemonic=None)
    db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title" BLOB, "done" BLOB)')
    return db


def _mk(i, node="a1b2c3d4e5f60718", row=None, col="title", value=None):
    return CrdtMessage(
        timestamp_to_string(Timestamp(BASE + i * 977, i % 4, node)),
        "todo", row or f"r{i % 23}", col, value if value is not None else f"v{i}",
    )


def test_mesh_sharded_winner_cache_parity_growth_and_shard_audit():
    """The sharded slot arrays must plan bit-identically to the
    single-device cache across overlapping batches (growth forced by a
    tiny initial capacity), keep cells spread over devices, and hold
    slot == SQLite MAX(timestamp) PER SHARD (the audit runs through the
    sharded gather; a per-shard sweep re-audits each placement group)."""
    from evolu_tpu.ops.winner_cache import DeviceWinnerCache, MeshShardedWinnerCache
    from evolu_tpu.storage.apply import apply_messages

    rng = np.random.default_rng(12)
    db_a, db_b = _client_db(), _client_db()
    ctx = MeshContext()
    cache_a = DeviceWinnerCache(db_a, capacity=64)
    cache_b = MeshShardedWinnerCache(db_b, mesh_ctx=ctx, capacity=16)
    tree_a, tree_b = {}, {}

    def _dump(db):
        return (db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'),
                db.exec('SELECT * FROM "todo" ORDER BY "id"'))

    try:
        for batch_no in range(4):
            order = rng.permutation(130)
            batch = tuple(_mk(int(i) + batch_no * 40) for i in order)
            tree_a = apply_messages(db_a, tree_a, batch, planner=cache_a.plan_batch)
            tree_b = apply_messages(db_b, tree_b, batch, planner=cache_b.plan_batch)
            assert _dump(db_a) == _dump(db_b), f"batch {batch_no}"
            assert merkle_tree_to_string(tree_a) == merkle_tree_to_string(tree_b)
        counts = cache_b.shard_slot_counts()
        assert sum(counts) == len(cache_b._slots)
        assert sum(1 for c in counts if c) >= 4, (
            f"cells did not spread over the mesh: {counts}"
        )
        # Whole-cache audit through the sharded gather, then per shard.
        assert cache_b.verify_against_db() == len(cache_b._slots)
        by_shard = {}
        for cell, slot in cache_b._slots.items():
            by_shard.setdefault(slot % cache_b.n_shards, []).append(cell)
        for si, cells in by_shard.items():
            for c in cells:
                assert cache_b._cell_shard(c) == si
        # Invalidation releases slots back to the owning shard only.
        victims = list(cache_b._slots)[:4]
        victim_shards = [cache_b._slots[c] % cache_b.n_shards for c in victims]
        cache_b.invalidate(victims)
        for si in victim_shards:
            assert cache_b._free_by_shard[si], "freed slot not returned per shard"
        batch = tuple(_mk(int(i)) for i in range(50))
        tree_a = apply_messages(db_a, tree_a, batch, planner=cache_a.plan_batch)
        tree_b = apply_messages(db_b, tree_b, batch, planner=cache_b.plan_batch)
        assert _dump(db_a) == _dump(db_b)
        assert cache_b.verify_against_db() == len(cache_b._slots)
        # The foreign-write reset gate must see per-shard FREED slots
        # even when nothing is live (review finding: the base gate read
        # `_free`, which the sharded subclass never populates).
        cache_b.invalidate(list(cache_b._slots))
        assert not cache_b._slots and any(cache_b._free_by_shard)
        assert cache_b._has_slot_state() is True
    finally:
        db_a.close()
        db_b.close()


def test_mesh_sharded_cache_jit_flat_within_bucket():
    """Satellite 2, cache half: `mesh_jit_cache_size` must stay flat
    across varying batch sizes within one bucket."""
    from evolu_tpu.ops.winner_cache import MeshShardedWinnerCache, mesh_jit_cache_size
    from evolu_tpu.storage.apply import apply_messages

    db = _client_db()
    # adaptive=False pins the cached path: the adaptive gate streams
    # first-contact batches (rate 1.0 > seed_hi), which would leave the
    # sharded kernels uncompiled and the fence vacuous.
    cache = MeshShardedWinnerCache(db, mesh_ctx=MeshContext(), capacity=256,
                                   adaptive=False)
    tree = {}
    try:
        tree = apply_messages(db, tree, tuple(_mk(i) for i in range(40)),
                              planner=cache.plan_batch)
        size0 = mesh_jit_cache_size()
        assert size0 > 0, "warm-up must have compiled the sharded cache kernels"
        for n in (3, 11, 23, 40):  # same per-shard bucket as the warm-up
            tree = apply_messages(db, tree, tuple(_mk(i) for i in range(n)),
                                  planner=cache.plan_batch)
        assert mesh_jit_cache_size() == size0, (
            "a varying batch size recompiled the sharded winner-cache kernels"
        )
    finally:
        db.close()


def test_worker_selects_sharded_cache_only_when_configured():
    """Config selection: default OFF (DeviceWinnerCache), mesh_engine
    → MeshShardedWinnerCache on a multi-device host."""
    from evolu_tpu.ops.winner_cache import DeviceWinnerCache, MeshShardedWinnerCache
    from evolu_tpu.runtime.worker import select_planner
    from evolu_tpu.utils.config import Config

    db = _client_db()
    try:
        default = select_planner(Config(backend="tpu"), db)
        assert type(default.cache) is DeviceWinnerCache
        sharded = select_planner(Config(backend="tpu", mesh_engine=True), db)
        assert type(sharded.cache) is MeshShardedWinnerCache
    finally:
        db.close()


# -- relay wiring + observability --


def test_relay_mesh_engine_default_off_and_env_override(monkeypatch):
    server = RelayServer(ShardedRelayStore(shards=1))
    try:
        assert server.mesh_engine is False
        assert server.scheduler is None  # default path untouched
    finally:
        server.store.close()
    monkeypatch.setenv("EVOLU_MESH_ENGINE", "1")
    server = RelayServer(ShardedRelayStore(shards=1))
    try:
        assert server.mesh_engine is True
        assert server.scheduler is not None  # implies batching
    finally:
        server.scheduler.stop()
        server.store.close()
    monkeypatch.setenv("EVOLU_MESH_ENGINE", "0")
    server = RelayServer(ShardedRelayStore(shards=1))
    try:
        assert server.mesh_engine is False
    finally:
        server.store.close()


def test_mesh_obs_family_and_stats_section():
    """Driving a sync through a mesh_engine relay must populate the
    `evolu_mesh_*` family and surface the /stats `mesh` section
    (devices gauge, dispatch counter, occupancy/padding histograms,
    cross-device reduce counters — docs/OBSERVABILITY.md)."""
    store = ShardedRelayStore(shards=2)
    server = RelayServer(store, mesh_ctx=MeshContext()).start()
    try:
        body = protocol.encode_sync_request(
            protocol.SyncRequest(_msgs("d" * 16, 0, 9), "obs-u", "d" * 16, "{}")
        )
        with urllib.request.urlopen(
            urllib.request.Request(
                server.url, data=body,
                headers={"Content-Type": "application/octet-stream"},
            ),
            timeout=60,
        ) as r:
            r.read()
        assert metrics.get_gauge("evolu_mesh_devices") >= 8
        assert metrics.get_counter("evolu_mesh_dispatches_total") > 0
        assert metrics.get_counter(
            "evolu_mesh_xdev_reduce_total", kind="digest") > 0
        with urllib.request.urlopen(server.url + "/stats", timeout=30) as r:
            stats = json.loads(r.read())
        mesh = stats["mesh"]
        assert mesh["devices"] >= 8
        assert mesh["dispatches_total"] > 0
        assert mesh["shard_rows"]["count"] > 0
        assert mesh["padding_waste_rows"]["count"] > 0
        assert mesh["xdev_reduce_total"]["digest"] > 0
    finally:
        server.stop()
        store.close()


def test_non_canonical_batch_bounces_before_side_effect_on_sharded_path():
    """The r5 contract, kept on the sharded path: a non-canonical
    timestamp width never enters a packed sharded batch — it dispatches
    as a singleton through the host-oracle route (and the response
    still serves)."""
    store = ShardedRelayStore(shards=2)
    server = RelayServer(store, mesh_ctx=MeshContext()).start()
    try:
        good = timestamp_to_string(Timestamp(BASE, 0, "e" * 16))
        bad_req = protocol.SyncRequest(
            (protocol.EncryptedCrdtMessage(good + "Z", b"x"),),
            "nc-u", "e" * 16, "{}",
        )
        body = protocol.encode_sync_request(bad_req)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                urllib.request.Request(
                    server.url, data=body,
                    headers={"Content-Type": "application/octet-stream"},
                ),
                timeout=60,
            )
        # Same answer the per-request relay gives (the storage-layer
        # timestamp parse, not the wire decoder, is what rejects the
        # width) — the sharded path must not change the error surface.
        assert ei.value.code == 500
        assert all(
            s.db.exec_sql_query('SELECT COUNT(*) AS n FROM "message"', ())[0]["n"] == 0
            for s in store.shards
        )
    finally:
        server.stop()
        store.close()
