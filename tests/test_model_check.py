"""Randomized end-to-end model check: mixed-backend replicas, random
interleavings of mutations and sync rounds, an offline stretch, and a
late-joining replica restored from the mnemonic — everything through
the REAL client/relay/HTTP stack. The reference never tests any
multi-node story (SURVEY.md §4); this is the strongest integration
property: total byte-level convergence from arbitrary schedules.
"""

import random
import time

import pytest

from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.runtime.client import create_evolu
from evolu_tpu.server.relay import RelayServer, ShardedRelayStore
from evolu_tpu.storage.clock import read_clock
from evolu_tpu.sync.client import connect
from evolu_tpu.utils.config import Config

SCHEMA = {"todo": ("title", "isCompleted", "categoryId"), "todoCategory": ("name",)}


def _dump(evolu):
    return (
        evolu.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'),
        evolu.db.exec('SELECT * FROM "todo" ORDER BY "id"'),
        evolu.db.exec('SELECT * FROM "todoCategory" ORDER BY "id"'),
    )


def _converge(replicas, deadline_s=40.0):
    """Sync rounds until every replica's history is byte-identical."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for r in replicas:
            r.sync()
            r.worker.flush()
        dumps = [r.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
                 for r in replicas]
        if all(d == dumps[0] for d in dumps):
            return
        time.sleep(0.05)
    raise AssertionError("replicas did not converge in time")


@pytest.mark.parametrize("seed", [1234, 99, 7])
def test_randomized_mixed_backend_schedules_converge(seed):
    rng = random.Random(seed)
    server = RelayServer(ShardedRelayStore(shards=4)).start()
    cfg = lambda **kw: Config(sync_url=server.url, **kw)  # noqa: E731
    a = create_evolu(SCHEMA, config=cfg(backend="tpu"))  # HBM winner cache
    b = create_evolu(SCHEMA, config=cfg(backend="cpu"), mnemonic=a.owner.mnemonic)
    c = create_evolu(SCHEMA, config=cfg(backend="auto", receive_chunk_size=40),
                     mnemonic=a.owner.mnemonic)
    replicas = [a, b, c]
    late = None
    # Pin that the HBM-cache route actually planned batches (the cache
    # may legitimately be EMPTY at the end: a livelock SyncError resets
    # it — the phantom-winner defense this test exists to exercise).
    cache = a.worker._planner.cache
    cache_calls = []
    orig_plan = cache.plan_batch
    cache.plan_batch = lambda *args, **kw: (cache_calls.append(1), orig_plan(*args, **kw))[1]
    try:
        for r in replicas:
            connect(r)
        row_ids: list = []
        offline = {id(b): False}
        b_transport = b._transport

        for step in range(60):
            r = rng.choice(replicas)
            op = rng.random()
            if op < 0.45 or not row_ids:
                row_ids.append(r.create("todo", {
                    "title": f"t{step}", "isCompleted": False,
                }))
            elif op < 0.7:
                r.update("todo", rng.choice(row_ids), {
                    "title": f"edit{step}", "isCompleted": bool(rng.getrandbits(1)),
                })
            elif op < 0.8:
                r.update("todo", rng.choice(row_ids), {"isDeleted": True})
            else:
                r.create("todoCategory", {"name": f"cat{step}"})
            r.worker.flush()
            if step == 20:
                # b drops FULLY off the network: detaching the
                # transport makes every push a no-op (the reference's
                # offline-swallow model), not just the explicit syncs.
                offline[id(b)] = True
                b._transport = None
            if step == 40:
                offline[id(b)] = False  # and returns with local edits
                b.attach_transport(b_transport)
            if rng.random() < 0.4:
                s = rng.choice(replicas)
                if not offline.get(id(s), False):
                    s.sync()
                    s.worker.flush()

        _converge(replicas)

        # A brand-new device restores from the mnemonic and must pull
        # the ENTIRE history (SURVEY.md §3.5).
        late = create_evolu(SCHEMA, config=cfg(backend="cpu"),
                            mnemonic=a.owner.mnemonic)
        connect(late)
        replicas.append(late)
        _converge(replicas)

        dumps = [_dump(r) for r in replicas]
        assert all(d == dumps[0] for d in dumps), "state diverged"
        # NB: cross-replica MERKLE TREE equality is deliberately NOT
        # asserted. The reference XORs a re-received non-winning
        # duplicate into the tree again (applyMessages.ts:104-122 — the
        # quirk merge.py reproduces), so under anti-entropy redelivery
        # the tree depends on each replica's delivery history, not just
        # the converged message set; the reference surfaces the
        # consequence as the SyncError livelock guard, which this
        # schedule can legitimately trip. Data convergence above is the
        # CRDT guarantee.
        assert cache_calls, "tpu replica's cache never engaged"
    finally:
        for r in replicas:
            r.dispose()
        server.stop()
