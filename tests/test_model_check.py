"""Randomized end-to-end model check: mixed-backend replicas, random
interleavings of mutations and sync rounds, an offline stretch, and a
late-joining replica restored from the mnemonic — everything through
the REAL client/relay/HTTP stack. The reference never tests any
multi-node story (SURVEY.md §4); this is the strongest integration
property: total byte-level convergence from arbitrary schedules.
"""

import random
import time
from contextlib import contextmanager

import pytest

from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.runtime.client import create_evolu
from evolu_tpu.server.relay import RelayServer, RelayStore, ShardedRelayStore
from evolu_tpu.storage.clock import read_clock
from evolu_tpu.sync.client import connect
from evolu_tpu.utils.config import Config

SCHEMA = {"todo": ("title", "isCompleted", "categoryId"), "todoCategory": ("name",)}


@contextmanager
def _evidence(label, seed):
    """Seed-replay evidence (ROADMAP #5): on assertion failure the
    episode dumps seed + flight-recorder ring + span export + metrics
    snapshot + conservation-ledger snapshot to a tmp artifact whose
    path rides the failure message — a failed seed arrives with its
    causal history, not just a stack.

    ISSUE 15: every episode is ALSO a conservation proof. The ledger
    resets at entry and, after the episode body finished (teardown
    included — quiescence), `ledger.audit()` must return ZERO violated
    equations: every message that entered any ingress reached exactly
    one terminal, on every route the episode exercised. Oracle-twin
    phases (reference replays, not traffic) run under
    `ledger.quarantine()`."""
    from evolu_tpu.obs import ledger

    ledger.reset()
    try:
        yield
    except AssertionError as e:
        from evolu_tpu.obs import trace

        path = trace.write_evidence(label, seed=seed)
        raise AssertionError(
            f"{e}\nseed={seed}; replay evidence artifact: {path}"
        ) from e
    violations = ledger.audit(at_barrier=True)
    if violations:
        from evolu_tpu.obs import trace

        path = trace.write_evidence(label + "-ledger", seed=seed)
        raise AssertionError(
            f"conservation ledger violated at episode end: {violations}\n"
            f"seed={seed}; replay evidence artifact: {path}"
        )


def _dump(evolu):
    return (
        evolu.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'),
        evolu.db.exec('SELECT * FROM "todo" ORDER BY "id"'),
        evolu.db.exec('SELECT * FROM "todoCategory" ORDER BY "id"'),
    )


def _converge(replicas, deadline_s=40.0):
    """Sync rounds until every replica's history is byte-identical."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for r in replicas:
            r.sync()
            r.worker.flush()
        dumps = [r.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
                 for r in replicas]
        if all(d == dumps[0] for d in dumps):
            return
        time.sleep(0.05)
    raise AssertionError("replicas did not converge in time")


@pytest.mark.parametrize("seed", [1234, 99, 7, 4242, 31337])
def test_randomized_mixed_backend_schedules_converge(seed):
    with _evidence("model-check", seed):
        _run_randomized_episode(seed)


def _run_randomized_episode(seed):
    rng = random.Random(seed)
    server = RelayServer(ShardedRelayStore(shards=4)).start()
    cfg = lambda **kw: Config(sync_url=server.url, **kw)  # noqa: E731
    a = create_evolu(SCHEMA, config=cfg(backend="tpu"))  # HBM winner cache
    b = create_evolu(SCHEMA, config=cfg(backend="cpu"), mnemonic=a.owner.mnemonic)
    c = create_evolu(SCHEMA, config=cfg(backend="auto", receive_chunk_size=40),
                     mnemonic=a.owner.mnemonic)
    # d routes receive batches >= 8 messages through the hot-owner
    # cell-range sharding over the 8-device virtual mesh (VERDICT r2
    # #5: a multi-device replica in the mix).
    d = create_evolu(SCHEMA, config=cfg(backend="auto", hot_owner_min_batch=8,
                                        min_device_batch=8),
                     mnemonic=a.owner.mnemonic)
    replicas = [a, b, c, d]
    late = None
    # Pin that the HBM-cache route actually planned batches (the cache
    # may legitimately be EMPTY at the end: a livelock SyncError resets
    # it — the phantom-winner defense this test exists to exercise).
    cache = a.worker._planner.cache
    cache_calls = []
    orig_plan = cache.plan_batch
    cache.plan_batch = lambda *args, **kw: (cache_calls.append(1), orig_plan(*args, **kw))[1]
    # Pin that the hot-owner route actually ran for d.
    from evolu_tpu.parallel import hot_owner as hot_mod

    hot_calls = []
    orig_hot = hot_mod.reconcile_hot_owner
    hot_mod.reconcile_hot_owner = (
        lambda *args, **kw: (hot_calls.append(1), orig_hot(*args, **kw))[1]
    )
    try:
        for r in replicas:
            connect(r)
        row_ids: list = []
        offline = {id(b): False}
        b_transport = b._transport

        for step in range(60):
            r = rng.choice(replicas)
            op = rng.random()
            if op < 0.45 or not row_ids:
                row_ids.append(r.create("todo", {
                    "title": f"t{step}", "isCompleted": False,
                }))
            elif op < 0.7:
                r.update("todo", rng.choice(row_ids), {
                    "title": f"edit{step}", "isCompleted": bool(rng.getrandbits(1)),
                })
            elif op < 0.8:
                r.update("todo", rng.choice(row_ids), {"isDeleted": True})
            else:
                r.create("todoCategory", {"name": f"cat{step}"})
            r.worker.flush()
            if step == 20:
                # b drops FULLY off the network: detaching the
                # transport makes every push a no-op (the reference's
                # offline-swallow model), not just the explicit syncs.
                offline[id(b)] = True
                b._transport = None
            if step == 40:
                offline[id(b)] = False  # and returns with local edits
                b.attach_transport(b_transport)
            if rng.random() < 0.4:
                s = rng.choice(replicas)
                if not offline.get(id(s), False):
                    s.sync()
                    s.worker.flush()

        # Deterministically engage d's hot-owner route before the
        # convergence phase: ONE batched mutation (a single Send, a
        # single relay push) lands >= 18 messages atomically, so d's
        # next pull receives them as one batch above
        # hot_owner_min_batch. Unbatched creates push per-Send and a
        # racing pull can see them in dribbles — found by a 20-seed
        # sweep.
        with a.batching():
            for j in range(6):
                a.create("todo", {"title": f"hot{j}"})
        a.worker.flush()

        _converge(replicas)

        # A brand-new device restores from the mnemonic and must pull
        # the ENTIRE history (SURVEY.md §3.5).
        late = create_evolu(SCHEMA, config=cfg(backend="cpu"),
                            mnemonic=a.owner.mnemonic)
        connect(late)
        replicas.append(late)
        _converge(replicas)

        dumps = [_dump(r) for r in replicas]
        assert all(d == dumps[0] for d in dumps), "state diverged"
        # NB: cross-replica MERKLE TREE equality is deliberately NOT
        # asserted. The reference XORs a re-received non-winning
        # duplicate into the tree again (applyMessages.ts:104-122 — the
        # quirk merge.py reproduces), so under anti-entropy redelivery
        # the tree depends on each replica's delivery history, not just
        # the converged message set; the reference surfaces the
        # consequence as the SyncError livelock guard, which this
        # schedule can legitimately trip. Data convergence above is the
        # CRDT guarantee.
        assert cache_calls, "tpu replica's cache never engaged"
        assert hot_calls, "hot-owner multi-device planner never engaged"
    finally:
        hot_mod.reconcile_hot_owner = orig_hot
        for r in replicas:
            r.dispose()
        server.stop()


def test_adversarial_clocks_through_two_relay_fleet_converge():
    """ROADMAP #5's named gap, small dose: regressing/stuttering HLC
    `now` schedules have only ever run against the pure timestamp unit
    tests — here one seeded schedule drives them through an end-to-end
    2-relay FLEET episode (placement ring, 307 redirects, learned
    client routes — server/fleet.py), asserting byte-identical
    convergence AND the winner-cache == MAX(timestamp) invariant on
    the device-backend replica."""
    with _evidence("model-check-adversarial-clocks", 20240731):
        _run_adversarial_clock_episode()


def _run_adversarial_clock_episode():
    import numpy as np

    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.obs import metrics
    from evolu_tpu.utils.config import FleetConfig

    seed = 20240731
    rng = random.Random(seed)
    base = int(time.time() * 1000)

    def adversarial_now(sub_seed):
        """Deterministic hostile wall clock: 40% frozen (stuttering —
        the HLC counter must absorb it), 20% regressing (bounded well
        under max_drift so the schedule stays in the legal envelope:
        total advance <= 60*500ms + regression floor 20s < 60s drift),
        else small advances."""
        r = random.Random(sub_seed)
        state = {"t": base}

        def now():
            roll = r.random()
            if roll < 0.4:
                pass  # stutter: frozen clock
            elif roll < 0.6:
                state["t"] = max(base - 20_000,
                                 state["t"] - r.randrange(0, 10_000))
            else:
                state["t"] += r.randrange(1, 500)
            return state["t"]

        return now

    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    # R=1: the shared owner has ONE authoritative relay; clients
    # pointed at the other must learn the route through a live 307.
    fleet_cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                            version=1)
    a.enable_fleet(fleet_cfg)
    b.enable_fleet(fleet_cfg)
    replicas = []
    try:
        # One device-backend replica (HBM winner cache engaged) homed
        # at relay a, one cpu replica at relay b: exactly one of them
        # starts on the wrong side of the ring.
        r1 = create_evolu(SCHEMA, config=Config(sync_url=a.url, backend="tpu"))
        r2 = create_evolu(SCHEMA, config=Config(sync_url=b.url, backend="cpu"),
                          mnemonic=r1.owner.mnemonic)
        replicas = [r1, r2]
        for i, r in enumerate(replicas):
            r.worker.now = adversarial_now(seed + i)
            connect(r)
        redirects_before = metrics.get_counter("evolu_sync_redirects_total")
        row_ids = []
        for step in range(60):
            r = rng.choice(replicas)
            if rng.random() < 0.5 or not row_ids:
                row_ids.append(r.create("todo", {
                    "title": f"adv{step}", "isCompleted": False,
                }))
            else:
                r.update("todo", rng.choice(row_ids), {
                    "title": f"advedit{step}",
                    "isCompleted": bool(rng.getrandbits(1)),
                })
            r.worker.flush()
            if rng.random() < 0.5:
                s = rng.choice(replicas)
                s.sync()
                s.worker.flush()
        _converge(replicas)
        # Quiesce BOTH loops before reading HBM cache arrays: a sync
        # round still in flight on the transport thread would plan a
        # batch concurrently, DONATING the very buffers this test is
        # about to read (donated jax arrays read as deleted).
        for r in replicas:
            r._transport.flush()
            r.worker.flush()
        dumps = [_dump(r) for r in replicas]
        assert dumps[0] == dumps[1], "state diverged under adversarial clocks"
        # The fleet was actually exercised: the replica homed at the
        # non-primary relay followed at least one 307 and cached the
        # route to the primary.
        assert metrics.get_counter(
            "evolu_sync_redirects_total") > redirects_before
        primary = a if a.fleet.ring.primary(r1.owner.id) == a.url else b
        assert primary.store.user_ids() == [r1.owner.id]
        other = b if primary is a else a
        assert other.store.user_ids() == []  # R=1: partitioned, not mirrored
        # Winner-cache == MAX(timestamp) per cell on the device
        # replica (CLAUDE.md invariant), read straight out of the HBM
        # slot arrays.
        cache = r1.worker._planner.cache
        w1 = np.asarray(cache._w1)
        w2 = np.asarray(cache._w2)
        checked = 0
        for (table, row, col), slot in cache._slots.items():
            got = r1.db.exec_sql_query(
                'SELECT MAX("timestamp") AS m FROM "__message" '
                'WHERE "table" = ? AND "row" = ? AND "column" = ?',
                (table, row, col),
            )[0]["m"]
            k1, k2 = int(w1[slot]), int(w2[slot])
            if k1 == 0 and k2 == 0:
                assert got is None, (table, row, col)
                continue
            cached_ts = timestamp_to_string(
                Timestamp(k1 >> 16, k1 & 0xFFFF, f"{k2:016x}")
            )
            assert cached_ts == got, (table, row, col)
            checked += 1
        # A livelock SyncError reset can legitimately empty the cache;
        # but the schedule above must at least have ENGAGED it.
        assert cache._slots or checked == 0
    finally:
        for r in replicas:
            r.dispose()
        a.stop()
        b.stop()


@pytest.mark.parametrize("seed,crash_at", [(5, 1), (11, 2), (47, 3)])
def test_crash_mid_chunked_receive_restart_converges(tmp_path, seed, crash_at):
    """Crash injection (VERDICT r2 #5): a replica pulling a large
    history in chunks dies at the Nth per-chunk clock persist — the
    crashing chunk's transaction rolls back, earlier chunks stay
    committed (rows + clock atomic per chunk). A RESTARTED process
    over the same database file must resume from the persisted clock
    and converge to byte-identical state."""
    with _evidence("model-check-crash-restart", seed):
        _run_crash_restart_episode(tmp_path, seed, crash_at)


def _run_crash_restart_episode(tmp_path, seed, crash_at):
    from evolu_tpu.runtime.client import Evolu
    import evolu_tpu.runtime.worker as worker_mod

    rng = random.Random(seed)
    server = RelayServer(ShardedRelayStore(shards=2)).start()
    src = vic = vic2 = None
    real_update = worker_mod.update_clock
    try:
        cfg = Config(sync_url=server.url)
        src = create_evolu(SCHEMA, config=cfg)
        connect(src)
        for i in range(rng.randrange(100, 140)):
            src.create("todo", {"title": f"t{i}", "isCompleted": bool(i % 2)})
        src.worker.flush()
        src.sync()
        src.worker.flush()
        src._transport.flush()

        # Victim: chunked receive (several 50-message chunks), crash
        # injected at the crash_at-th per-chunk clock persist.
        vic_path = str(tmp_path / "victim.db")
        vcfg = Config(sync_url=server.url, receive_chunk_size=50)
        vic = Evolu(db_path=vic_path, config=vcfg, mnemonic=src.owner.mnemonic)
        vic.update_db_schema(SCHEMA)
        calls = {"n": 0}

        def crashing_update(db, clock):
            calls["n"] += 1
            if calls["n"] == crash_at:
                raise RuntimeError("injected crash: died before clock persist")
            return real_update(db, clock)

        worker_mod.update_clock = crashing_update
        errors = []
        vic.subscribe_error(errors.append)
        connect(vic)
        deadline = time.time() + 20
        while time.time() < deadline and not errors:
            vic.sync()
            vic.worker.flush()
            vic._transport.flush()
            vic.worker.flush()
            time.sleep(0.02)
        assert errors, "injected crash never fired"
        worker_mod.update_clock = real_update

        partial = vic.db.exec('SELECT COUNT(*) FROM "__message"')[0][0]
        total = src.db.exec('SELECT COUNT(*) FROM "__message"')[0][0]
        if crash_at == 1:
            # Dying at the FIRST per-chunk clock persist rolls that
            # whole chunk back: the crash leaves a clean zero state,
            # and restart re-syncs from scratch.
            assert partial == 0, (partial, total)
        else:
            assert 0 < partial < total, (partial, total)
        # The committed prefix must be digest-coherent: the persisted
        # tree covers exactly the stored rows (resume invariant).
        from evolu_tpu.core.merkle import (
            create_initial_merkle_tree, insert_into_merkle_tree,
        )
        from evolu_tpu.core.timestamp import timestamp_from_string

        clock = read_clock(vic.db)
        expect = create_initial_merkle_tree()
        for (ts,) in vic.db.exec('SELECT "timestamp" FROM "__message" ORDER BY "timestamp"'):
            expect = insert_into_merkle_tree(timestamp_from_string(ts), expect)
        assert merkle_tree_to_string(clock.merkle_tree) == merkle_tree_to_string(expect)
        vic.dispose()  # the "process" is gone

        # Restart over the same file: resume from the persisted clock.
        vic2 = Evolu(db_path=vic_path, config=vcfg, mnemonic=src.owner.mnemonic)
        vic2.update_db_schema(SCHEMA)
        connect(vic2)
        _converge([src, vic2])
        assert (
            vic2.db.exec('SELECT * FROM "todo" ORDER BY "id"')
            == src.db.exec('SELECT * FROM "todo" ORDER BY "id"')
        )
    finally:
        worker_mod.update_clock = real_update
        for r in (src, vic, vic2):
            if r is not None:
                try:
                    r.dispose()
                except Exception:  # noqa: BLE001,S110 - vic may already be disposed
                    pass
        server.stop()


def test_mixed_crdt_workload_adversarial_clocks_two_relay_fleet():
    """ISSUE 7 satellite (ROADMAP #5 small dose): LWW + PN-counter +
    AW-set columns under regressing/stuttering HLC clocks through a
    2-relay FLEET episode. Asserts byte-identical convergence of app
    tables AND __crdt_* merge state, counter EXACTNESS (the materialized
    value equals the sum of every acked increment), the AW-set add-wins
    outcome for a concurrent add/remove pair, and the per-type
    winner-cache contract on the device-backend replica."""
    with _evidence("model-check-mixed-crdt", 20250804):
        _run_mixed_crdt_episode()


def _run_mixed_crdt_episode():
    import numpy as np

    from evolu_tpu.core import crdt_types as ct
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.utils.config import FleetConfig

    seed = 20250804
    rng = random.Random(seed)
    base = int(time.time() * 1000)

    def adversarial_now(sub_seed):
        r = random.Random(sub_seed)
        state = {"t": base}

        def now():
            roll = r.random()
            if roll < 0.4:
                pass  # stutter: frozen clock
            elif roll < 0.6:
                state["t"] = max(base - 20_000,
                                 state["t"] - r.randrange(0, 10_000))
            else:
                state["t"] += r.randrange(1, 400)
            return state["t"]

        return now

    schema = {"todo": ("title", "isCompleted"),
              "metrics": ("name", "clicks:counter", "tags:awset")}
    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    fleet_cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                            version=1)
    a.enable_fleet(fleet_cfg)
    b.enable_fleet(fleet_cfg)
    replicas = []
    errors = []
    try:
        r1 = create_evolu(schema, config=Config(sync_url=a.url, backend="tpu"))
        r2 = create_evolu(schema, config=Config(sync_url=b.url, backend="cpu"),
                          mnemonic=r1.owner.mnemonic)
        replicas = [r1, r2]
        for i, r in enumerate(replicas):
            r.worker.now = adversarial_now(seed + i)
            r.subscribe_error(errors.append)
            connect(r)
        counter_rows = []
        expected_sum = {}
        for r in replicas:
            rid = r.create("metrics", {"name": f"m-{id(r)}"})
            r.worker.flush()
            counter_rows.append(rid)
            expected_sum[rid] = 0
        lww_rows = []
        for step in range(70):
            r = rng.choice(replicas)
            roll = rng.random()
            if roll < 0.25 or not lww_rows:
                lww_rows.append(r.create("todo", {
                    "title": f"t{step}", "isCompleted": False}))
            elif roll < 0.40:
                r.update("todo", rng.choice(lww_rows), {
                    "title": f"e{step}",
                    "isCompleted": bool(rng.getrandbits(1))})
            elif roll < 0.70:
                rid = rng.choice(counter_rows)
                d = rng.randrange(-50, 51)
                r.increment("metrics", rid, "clicks", d)
                expected_sum[rid] += d
            elif roll < 0.85:
                r.set_add("metrics", rng.choice(counter_rows), "tags",
                          rng.choice("abcd"))
            else:
                rid = rng.choice(counter_rows)
                elem = rng.choice("abcd")
                r.set_remove("metrics", rid, "tags", elem)
            r.worker.flush()
            if rng.random() < 0.5:
                s = rng.choice(replicas)
                s.sync()
                s.worker.flush()
        _converge(replicas)

        # Concurrent add/remove → ADD WINS: both replicas know tag T1;
        # r2 removes (observing only T1) while r1 concurrently re-adds.
        aw_row = counter_rows[0]
        r1.set_add("metrics", aw_row, "tags", "awinner")
        r1.worker.flush()
        _converge(replicas)
        r2.set_remove("metrics", aw_row, "tags", "awinner")  # observes T1 only
        r1.set_add("metrics", aw_row, "tags", "awinner")     # concurrent T2
        r1.worker.flush()
        r2.worker.flush()
        _converge(replicas)
        for r in replicas:
            r._transport.flush()
            r.worker.flush()

        # The only tolerated errors are the livelock SyncError guard
        # (redelivery quirk, reference semantics) — a drift/overflow
        # error would mean an increment was NOT acked.
        from evolu_tpu.core.types import SyncError
        real = [e for e in errors if not isinstance(e, SyncError)]
        assert not real, real

        dumps = []
        for r in replicas:
            dumps.append((
                r.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'),
                r.db.exec('SELECT * FROM "todo" ORDER BY "id"'),
                r.db.exec('SELECT * FROM "metrics" ORDER BY "id"'),
                r.db.exec('SELECT * FROM "__crdt_counter" ORDER BY "row","column"'),
                r.db.exec('SELECT * FROM "__crdt_set" ORDER BY "tag"'),
                r.db.exec('SELECT * FROM "__crdt_kill" ORDER BY "tag"'),
            ))
        assert dumps[0] == dumps[1], "typed state diverged under adversarial clocks"

        # Counter EXACTNESS: materialized value == sum of acked increments.
        for rid, total in expected_sum.items():
            got = r1.db.exec_sql_query(
                'SELECT "clicks" FROM "metrics" WHERE "id" = ?', (rid,)
            )[0]["clicks"]
            assert got == total, (rid, got, total)

        # Add-wins outcome: the concurrently re-added element survives.
        tags = r1.db.exec_sql_query(
            'SELECT "tags" FROM "metrics" WHERE "id" = ?', (aw_row,))[0]["tags"]
        assert '"awinner"' in tags, tags

        # Fold integrity: rebuilding state from the full log is a no-op.
        schema_r1 = ct.load_schema(r1.db)
        before = r1.db.exec('SELECT * FROM "__crdt_set" ORDER BY "tag"')
        ct.rebuild_state(r1.db, schema_r1)
        assert r1.db.exec('SELECT * FROM "__crdt_set" ORDER BY "tag"') == before

        # Winner-cache contract per type on the device replica: slot ==
        # MAX(timestamp) for LWW and typed cells alike (the xor gate),
        # while typed app values are the fold (asserted above).
        cache = r1.worker._planner.cache
        w1 = np.asarray(cache._w1)
        w2 = np.asarray(cache._w2)
        typed_checked = 0
        for (table, row, col), slot in cache._slots.items():
            got = r1.db.exec_sql_query(
                'SELECT MAX("timestamp") AS m FROM "__message" '
                'WHERE "table" = ? AND "row" = ? AND "column" = ?',
                (table, row, col))[0]["m"]
            k1, k2 = int(w1[slot]), int(w2[slot])
            if k1 == 0 and k2 == 0:
                assert got is None, (table, row, col)
                continue
            cached_ts = timestamp_to_string(
                Timestamp(k1 >> 16, k1 & 0xFFFF, f"{k2:016x}"))
            assert cached_ts == got, (table, row, col)
            if schema_r1.is_typed(table, col):
                typed_checked += 1
        # A livelock reset can legitimately empty the cache; the
        # schedule must merely have engaged it (same tolerance as the
        # adversarial-clock fleet test above).
        assert cache._slots or typed_checked == 0
    finally:
        for r in replicas:
            r.dispose()
        a.stop()
        b.stop()


def test_list_crdt_partition_heal_adversarial_clocks_episode():
    """ISSUE 14 satellite (ROADMAP #5 dose): the RGA list type through
    a 2-relay FLEET under regressing/stuttering HLC clocks, a PARTITION
    stretch (both replicas mutate offline, with concurrent interleaved
    inserts at the SAME anchor and a delete racing an insert anchored
    on the deleted element), a NON-CANONICAL batch bouncing to the host
    oracle mid-partition, then heal. Asserts byte-identical convergence
    of app + __crdt_list state, winner-cache == MAX(timestamp) on the
    device replica, and list materialization == the pure host-oracle
    replay of the merged op log."""
    with _evidence("model-check-list-crdt", 20260805):
        _run_list_crdt_episode()


def _run_list_crdt_episode():
    import numpy as np

    from evolu_tpu.core import crdt_list as cl
    from evolu_tpu.core.merkle import create_initial_merkle_tree
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.obs import metrics
    from evolu_tpu.utils.config import FleetConfig

    seed = 20260805
    rng = random.Random(seed)
    base = int(time.time() * 1000)

    def adversarial_now(sub_seed):
        r = random.Random(sub_seed)
        state = {"t": base}

        def now():
            roll = r.random()
            if roll < 0.4:
                pass  # stutter: frozen clock
            elif roll < 0.6:
                state["t"] = max(base - 20_000,
                                 state["t"] - r.randrange(0, 10_000))
            else:
                state["t"] += r.randrange(1, 400)
            return state["t"]

        return now

    schema = {"doc": ("title", "body:list")}
    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    fleet_cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                            version=1)
    a.enable_fleet(fleet_cfg)
    b.enable_fleet(fleet_cfg)
    replicas = []
    errors = []
    try:
        r1 = create_evolu(schema, config=Config(sync_url=a.url, backend="tpu"))
        r2 = create_evolu(schema, config=Config(sync_url=b.url, backend="cpu"),
                          mnemonic=r1.owner.mnemonic)
        replicas = [r1, r2]
        for i, r in enumerate(replicas):
            r.worker.now = adversarial_now(seed + i)
            r.subscribe_error(errors.append)
            connect(r)

        # Phase 1 (online): seed a shared document so both sides know
        # the same anchors, and keep syncing.
        row = r1.create("doc", {"title": "shared"})
        for v in ("a", "b", "c", "d"):
            r1.list_append("doc", row, "body", v)
        r1.worker.flush()
        _converge(replicas)
        elems = r1.list_elements("doc", row, "body")
        assert [v for _t, v in elems] == ["a", "b", "c", "d"]
        anchor = elems[1][0]        # "b" — the contested anchor
        victim = elems[2][0]        # "c" — deleted on one side, anchored on the other

        # Phase 2 (PARTITION): no sync rounds. Both replicas interleave
        # inserts at the SAME anchor; r2 deletes the element r1 keeps
        # anchoring on (tombstone-position semantics under fire).
        r2.list_delete("doc", row, "body", victim)
        for step in range(24):
            r = replicas[step % 2]
            roll = rng.random()
            if roll < 0.55:
                r.list_insert("doc", row, "body",
                              f"p{(step % 2) + 1}-{step}", after=anchor)
            elif roll < 0.75:
                r.list_insert("doc", row, "body",
                              f"v{(step % 2) + 1}-{step}", after=victim)
            else:
                r.list_append("doc", row, "body", f"t{(step % 2) + 1}-{step}")
            r.worker.flush()

        # Mid-partition hostile case: a NON-CANONICAL (uppercase node
        # hex) remote batch — LWW cells bounce the device planner to
        # the host oracle (winner-cache invalidation included on the
        # tpu replica) and a list op proves the fold is case-blind
        # (dedup is by raw string). Injected into BOTH replicas so the
        # merged histories stay identical.
        bounces_before = metrics.get_counter("evolu_merge_host_fallbacks_total")
        empty_tree = merkle_tree_to_string(create_initial_merkle_tree())

        def nc_ts(i):
            s = timestamp_to_string(
                Timestamp(base + 5000 + i, i, "00000000000000ab"))
            return s[:30] + s[30:].upper()

        hostile = tuple(
            [CrdtMessage(nc_ts(j), "doc", "remrow", "title", f"h{j}")
             for j in range(3)]
            + [CrdtMessage(nc_ts(7), "doc", "remrow", "body",
                           cl.list_insert_value("ghostwrite"))])
        for r in replicas:
            r.receive(hostile, empty_tree)
            r.worker.flush()
        assert metrics.get_counter(
            "evolu_merge_host_fallbacks_total") > bounces_before

        # Phase 3 (HEAL): sync rounds resume; fleet routing (R=1, one
        # authoritative relay) carries both sides to one history.
        _converge(replicas)
        for r in replicas:
            r._transport.flush()
            r.worker.flush()

        from evolu_tpu.core.types import SyncError
        real = [e for e in errors if not isinstance(e, SyncError)]
        assert not real, real

        dumps = []
        for r in replicas:
            dumps.append((
                r.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'),
                r.db.exec('SELECT * FROM "doc" ORDER BY "id"'),
                r.db.exec('SELECT * FROM "__crdt_list" ORDER BY "tag"'),
                r.db.exec('SELECT * FROM "__crdt_list_kill" ORDER BY "tag"'),
            ))
        assert dumps[0] == dumps[1], "list state diverged after partition/heal"

        # List materialization == the pure host-oracle replay of the
        # merged log (the fold is a function of the op SET alone).
        body_rows = r1.db.exec_sql_query(
            'SELECT "timestamp", "table", "row", "column", "value" '
            'FROM "__message" WHERE "table" = ? AND "column" = ?',
            ("doc", "body"))
        replayed = cl.replay_log([
            CrdtMessage(r["timestamp"], r["table"], r["row"], r["column"],
                        r["value"]) for r in body_rows])
        assert replayed, "episode produced no list traffic"
        for (_t, rid, _c), val in replayed.items():
            got = r1.db.exec_sql_query(
                'SELECT "body" FROM "doc" WHERE "id" = ?', (rid,))[0]["body"]
            assert got == val, (rid, got, val)

        # Both partition sides' same-anchor inserts survived, and the
        # deleted anchor's tombstone still anchored its children.
        final = [v for _t, v in r1.list_elements("doc", row, "body")]
        assert any(v.startswith("p1-") for v in final)
        assert any(v.startswith("p2-") for v in final)
        assert any(v.startswith("v") for v in final)
        assert "c" not in final  # the victim stayed deleted
        # The non-canonical list op folded into its own row's cell.
        assert r1.db.exec_sql_query(
            'SELECT "body" FROM "doc" WHERE "id" = ?',
            ("remrow",))[0]["body"] == '["ghostwrite"]'

        # Winner-cache == MAX(timestamp) on the device replica.
        cache = r1.worker._planner.cache
        w1 = np.asarray(cache._w1)
        w2 = np.asarray(cache._w2)
        for (table, rr, col), slot in cache._slots.items():
            got = r1.db.exec_sql_query(
                'SELECT MAX("timestamp") AS m FROM "__message" '
                'WHERE "table" = ? AND "row" = ? AND "column" = ?',
                (table, rr, col))[0]["m"]
            k1, k2 = int(w1[slot]), int(w2[slot])
            if k1 == 0 and k2 == 0:
                assert got is None, (table, rr, col)
                continue
            cached_ts = timestamp_to_string(
                Timestamp(k1 >> 16, k1 & 0xFFFF, f"{k2:016x}"))
            assert cached_ts == got, (table, rr, col)
    finally:
        for r in replicas:
            r.dispose()
        a.stop()
        b.stop()


def test_no_stale_query_results_adversarial_clocks_host_bounce():
    """ISSUE 9 satellite (ROADMAP #5 small dose): one seeded adversarial
    episode through the changed-set-gated query invalidation layer —
    regressing/stuttering HLC `now`, a NON-CANONICAL remote batch
    bouncing to the host oracle mid-stream (winner-cache invalidation
    included: backend="tpu"), a rolled-back Send, and eviction churn —
    driving TWIN workers (gated vs the re-run-everything oracle) over
    the identical command schedule. NO stale query result may ever be
    delivered: the gated worker's output stream must be byte-identical
    to the oracle's at every step, and at the end every cached
    subscription must equal a fresh SQL read of the live database."""
    with _evidence("model-check-stale-query", 20260804):
        _run_stale_query_episode()


def _run_stale_query_episode():
    from dataclasses import replace as dc_replace

    from evolu_tpu.core.merkle import create_initial_merkle_tree, merkle_tree_to_string
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.core.types import (CrdtClock, CrdtMessage, NewCrdtMessage,
                                      TableDefinition)
    from evolu_tpu.obs import metrics
    from evolu_tpu.runtime import messages as msg
    from evolu_tpu.runtime.worker import DbWorker
    from evolu_tpu.storage.clock import read_clock, update_clock
    from evolu_tpu.storage.native import open_database

    seed = 20260804
    base = 1_700_000_000_000
    empty_tree = merkle_tree_to_string(create_initial_merkle_tree())
    mnemonic = ("abandon abandon abandon abandon abandon abandon "
                "abandon abandon abandon abandon abandon about")
    tds = (TableDefinition.of("todo", ("title", "done")),
           TableDefinition.of("other", ("name",)))

    def adversarial_now(sub_seed):
        """Deterministic hostile wall clock (same envelope as the fleet
        episode above): 40% frozen, 20% bounded regression, else small
        advances. Gating never changes how often the worker samples
        `now`, so twin workers with the same sub_seed stamp identical
        timestamps — any divergence would itself be a bug."""
        r = random.Random(sub_seed)
        state = {"t": base}

        def now():
            roll = r.random()
            if roll < 0.4:
                pass  # stutter: frozen clock
            elif roll < 0.6:
                state["t"] = max(base - 20_000,
                                 state["t"] - r.randrange(0, 5_000))
            else:
                state["t"] += r.randrange(1, 400)
            return state["t"]

        return now

    def make_worker(gated):
        db = open_database(":memory:")
        outputs, pushes = [], []
        w = DbWorker(db, config=Config(backend="tpu", query_invalidation=gated),
                     on_output=outputs.append, post_sync=pushes.append,
                     now=adversarial_now(seed))
        w.start(mnemonic)
        w.stop()  # drive handle() synchronously: deterministic twin runs
        clock = read_clock(db)
        with db.transaction():  # pin the HLC node id across the twins
            update_clock(db, CrdtClock(
                dc_replace(clock.timestamp, node="00c0ffee00c0ffee"),
                clock.merkle_tree))
        w.handle(msg.UpdateDbSchema(tds))
        outputs.clear()
        return w, outputs, pushes

    def remote_ts(i, counter=0, upper=False):
        s = timestamp_to_string(
            Timestamp(base + i, counter, "00000000000000ab"))
        return s[:30] + s[30:].upper() if upper else s

    qs = tuple(
        [msg.serialize_query('SELECT "id", "title", "done" FROM "todo" '
                             'WHERE "id" = ?', (f"row{i}",)) for i in range(8)]
        + [msg.serialize_query('SELECT "id", "title" FROM "todo" '
                               'WHERE "done" = ? ORDER BY "title"', (i,))
           for i in range(4)]
        + [msg.serialize_query('SELECT "id", "name" FROM "other" ORDER BY "id"')])

    rng = random.Random(seed)
    schedule = [msg.Query(qs)]
    for step in range(48):
        roll = rng.random()
        if roll < 0.40:
            table, row = ("todo", f"row{rng.randrange(12)}") if roll < 0.30 \
                else ("other", f"o{rng.randrange(3)}")
            col = "title" if table == "todo" else "name"
            schedule.append(msg.Send(
                (NewCrdtMessage(table, row, col, f"v{step}"),), (), qs))
        elif roll < 0.55:
            schedule.append(msg.Send(
                (NewCrdtMessage("todo", f"row{rng.randrange(12)}", "done",
                                rng.randrange(2)),), (f"cb{step}",), qs))
        elif roll < 0.70:
            schedule.append(msg.Query(qs))
        elif roll < 0.80:
            batch = tuple(
                CrdtMessage(remote_ts(1000 + step * 10 + j, counter=j),
                            "todo", f"rem{j % 2}", "title", f"m{step}.{j}")
                for j in range(3))
            schedule.append(msg.Receive(batch, empty_tree))
            schedule.append(msg.Query(qs))
        elif roll < 0.90:
            schedule.append(msg.EvictQueries((rng.choice(qs),)))
            schedule.append(msg.Query(qs))
        else:
            # un-encodable value: the Send rolls back before any write
            schedule.append(msg.Send(
                (NewCrdtMessage("todo", "row0", "title", b"\x00"),), (), qs))
            schedule.append(msg.Query(qs))
    # The named mid-stream hostile case: NON-CANONICAL hex timestamps
    # bounce the batch to the host oracle and invalidate winner-cache
    # cells; more gated sweeps follow it.
    schedule[len(schedule) // 2:len(schedule) // 2] = [
        msg.Receive(tuple(
            CrdtMessage(remote_ts(9000 + j, counter=j, upper=True),
                        "todo", "row1", "done", j) for j in range(3)),
            empty_tree),
        msg.Query(qs),
    ]

    skips_before = sum(metrics.get_counter(k) for k in (
        "evolu_query_skipped_by_table_total",
        "evolu_query_skipped_by_rows_total",
        "evolu_query_skipped_clean_total"))
    bounces_before = metrics.get_counter("evolu_merge_host_fallbacks_total")
    w_gated, out_g, push_g = make_worker(True)
    w_naive, out_n, push_n = make_worker(False)
    try:
        for cmd in schedule:
            w_gated.handle(cmd)
            w_naive.handle(cmd)
        # Byte-identical delivery: same outputs (OnError compared by
        # type — exception objects don't compare equal), same pushes.
        assert [type(o).__name__ for o in out_g] \
            == [type(o).__name__ for o in out_n]
        stream_g = [o for o in out_g if not isinstance(o, msg.OnError)]
        stream_n = [o for o in out_n if not isinstance(o, msg.OnError)]
        assert stream_g == stream_n, \
            "gated patch stream diverged from the re-exec oracle"
        assert push_g == push_n
        for sql in ('SELECT * FROM "__message" ORDER BY "timestamp"',
                    'SELECT * FROM "todo" ORDER BY "id"',
                    'SELECT * FROM "other" ORDER BY "id"'):
            assert w_gated.db.exec(sql) == w_naive.db.exec(sql)
        # Direct no-staleness oracle: every cached subscription equals
        # a fresh read of the live database RIGHT NOW.
        for q in qs:
            if q not in w_gated.queries_rows_cache:
                continue  # evicted by churn; next sweep root-replaces
            sql, params = msg.deserialize_query(q)
            assert w_gated.queries_rows_cache[q] \
                == w_gated.db.exec_sql_query(sql, params), q
        # The episode actually exercised the gate (skips happened) AND
        # the named hostile route (host-oracle bounce mid-stream).
        assert sum(metrics.get_counter(k) for k in (
            "evolu_query_skipped_by_table_total",
            "evolu_query_skipped_by_rows_total",
            "evolu_query_skipped_clean_total")) > skips_before
        assert metrics.get_counter(
            "evolu_merge_host_fallbacks_total") > bounces_before
    finally:
        w_gated.db.close()
        w_naive.db.close()


# -- PR-11 torture: the write-behind queue's durability license --


@pytest.mark.slow
@pytest.mark.parametrize("seed", [3, 17, 71])
def test_write_behind_sigkill_torture(tmp_path, seed):
    """SIGKILL a write-behind relay worker at an arbitrary point
    (mid-queue, mid-drain, mid-checkpoint — the drain is slowed and
    checkpoints run behind the barrier every 4 batches), restart it,
    and demand the drained SQLite end state be byte-identical (state
    crc) to a synchronous-apply oracle twin of the ACKed prefix. The
    ACK point is the record-log fsync: a kill can land between the
    fsync and the ACK print, so prefix+1 is also an accepted oracle.
    This is the license for promoting device state to truth
    (ROADMAP #1): an ACKed write is never lost, and replay's
    always-exact tree fold converges to the oracle regardless of
    where the kill landed."""
    with _evidence("write-behind-sigkill", seed):
        _run_write_behind_torture(tmp_path, seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [7, 29, 101])
def test_write_behind_sharded_sigkill_torture(tmp_path, seed):
    """PR-19: the same SIGKILL episode against a 3-shard store with
    one drain worker per shard. The kill can now land with shard k's
    transaction committed and shard j's still pending (workers drain
    concurrently) — replay must heal the partial commit exactly:
    committed rows re-classify as duplicates, the end state is still
    byte-identical to a synchronous oracle of the ACKed prefix (or
    prefix+1 — fsync-before-ACK-print), and the finish process's
    episode audit stays clean."""
    with _evidence("write-behind-sharded-sigkill", seed):
        _run_write_behind_torture(tmp_path, seed, shards=3, workers=3)


def _run_write_behind_torture(tmp_path, seed, shards=1, workers=0):
    import os
    import signal
    import subprocess
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _write_behind_worker import seeded_batches, state_crc

    from evolu_tpu.server.engine import BatchReconciler

    rng = random.Random(seed)
    n_batches = 12
    db_path = str(tmp_path / "victim.db")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_write_behind_worker.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, worker, "ingest", db_path, str(seed),
         str(n_batches), "0.15", str(shards), str(workers)],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    kill_after = rng.randrange(1, n_batches - 1)
    acked = -1
    try:
        for line in proc.stdout:
            if line.startswith("ACK "):
                acked = int(line.split()[1])
                if acked >= kill_after:
                    # Land the kill anywhere in the next batches'
                    # serve/drain/checkpoint window.
                    time.sleep(rng.random() * 0.3)
                    proc.kill()  # SIGKILL — no teardown, no flush
                    break
            elif line.startswith("DONE"):
                break
        # The worker may have ACKed more batches into the pipe before
        # dying than the loop above consumed — the TRUE acked count is
        # the last ACK line anywhere in its output.
        for line in proc.stdout:
            if line.startswith("ACK "):
                acked = int(line.split()[1])
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert acked >= 0, "worker never ACKed a batch"

    # Restart: constructor replay + flush, then the state crc.
    out = subprocess.run(
        [sys.executable, worker, "finish", db_path, str(shards),
         str(workers)],
        capture_output=True, text=True, timeout=300, env=env, check=True,
    )
    done = [ln for ln in out.stdout.splitlines() if ln.startswith("DONE crc=")]
    assert done, out.stdout
    got_crc = done[-1].split("crc=")[1]

    # Oracle twins: synchronous apply of the ACKed prefix — and of
    # prefix+1 (a kill between the log fsync and the ACK print means
    # one more batch is legitimately durable). The kill may also land
    # mid-append of batch acked+1: each record is crc-framed, so a
    # torn frame is discarded at replay — on a single-shard store the
    # batch is ONE record (fully durable or absent, exactly the two
    # twins above). A sharded store appends one record PER LIVE SHARD
    # (ascending shard order) under one fsync, and a kill mid-append
    # can leave a complete frame PREFIX of that batch on disk (the
    # kernel's page cache survives process death), so every
    # record-prefix of batch acked+1 is also an accepted twin. The
    # restriction is well-defined: in-batch dedup never crosses
    # shards (its key includes the owner, and an owner's rows all
    # land in one shard).
    batches = seeded_batches(seed, n_batches)
    accepted = set()
    from evolu_tpu.obs import ledger as ledger_mod

    def _twin(prefix_batches, partial_reqs=None):
        oracle = RelayStore()
        eng = BatchReconciler(oracle)
        for reqs in prefix_batches:
            eng.run_batch_wire(reqs)
        if partial_reqs:
            eng.run_batch_wire(partial_reqs)
        crc = f"{state_crc(oracle):08x}"
        eng.close()
        oracle.close()
        return crc

    with ledger_mod.quarantine():  # reference computation, not traffic
        for extra in (0, 1):
            accepted.add(_twin(batches[: acked + 1 + extra]))
        if shards > 1 and acked + 1 < len(batches):
            import zlib as _zlib

            def shard_of(u):
                return _zlib.crc32(u.encode("utf-8")) % shards

            nxt = batches[acked + 1]
            live = sorted({shard_of(r.user_id) for r in nxt if r.messages})
            for r in range(1, len(live)):
                allow = set(live[:r])
                sub = [q for q in nxt if shard_of(q.user_id) in allow]
                accepted.add(_twin(batches[: acked + 1], sub))
    assert got_crc in accepted, (got_crc, accepted, acked)


def test_mixed_traffic_ledger_conservation_episode(tmp_path):
    """ISSUE 15's dedicated conservation episode: one relay process
    sees EVERY hostile flow at once — a write-behind log inherited from
    a SIGKILLed predecessor (restart replay), canonical pushes with
    exact redeliveries, a non-canonical-width reject, a poisoned engine
    pass retried as singletons, and a 503 backpressure shed — and the
    ledger must still prove conservation: replayed records reconcile
    (classify as duplicates where a pre-kill drain already committed
    them) rather than double-count, every terminal fires exactly once
    per delivery attempt, wb.queued == wb.drained at the barrier, and
    `ledger.audit()` returns zero violated equations."""
    with _evidence("ledger-mixed-traffic", 20260805):
        _run_mixed_ledger_episode(tmp_path, 20260805)


def _run_mixed_ledger_episode(tmp_path, seed):
    import os
    import subprocess
    import sys
    import urllib.error
    import urllib.request

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from evolu_tpu.obs import ledger as ledger_mod
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.sync import protocol

    # --- phase 1: a write-behind relay worker dies by SIGKILL with
    # ACKed-but-undrained records in its durable log. ---
    db_path = str(tmp_path / "mixed.db")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_write_behind_worker.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, worker, "ingest", db_path, str(seed), "6", "0.2"],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    acked = -1
    try:
        for line in proc.stdout:
            if line.startswith("ACK "):
                acked = int(line.split()[1])
                if acked >= 2:
                    time.sleep(0.15)  # land mid-drain
                    proc.kill()
                    break
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert acked >= 0, "worker never ACKed a batch"
    log_bytes = os.path.getsize(db_path + ".wblog")
    assert log_bytes > 16, "SIGKILL left no undrained log to replay"

    ledger_mod.reset()  # the proof window starts at the restart

    # --- phase 2: restart over the same store + log. The constructor
    # replays the predecessor's records (ingress.replay), classifying
    # rows a pre-kill drain already committed as store.duplicate —
    # reconciled, never double-counted. ---
    from evolu_tpu.server.relay import RelayServer, RelayStore

    orig_rbw = BatchReconciler.run_batch_wire
    poison = {"armed": False, "fired": 0}

    def flaky(self, requests):
        if poison["armed"] and not poison["fired"]:
            poison["fired"] += 1
            raise RuntimeError("injected poisoned batch")
        return orig_rbw(self, requests)

    BatchReconciler.run_batch_wire = flaky
    server = RelayServer(RelayStore(db_path), write_behind=True).start()
    try:
        t = ledger_mod.totals()
        replayed = t.get(ledger_mod.INGRESS_REPLAY, 0)
        assert replayed > 0, "restart replayed nothing"
        assert (t.get(ledger_mod.STORE_INSERTED, 0)
                + t.get(ledger_mod.STORE_DUPLICATE, 0)) == replayed

        def post(req, expect_error=None):
            body = protocol.encode_sync_request(req)
            try:
                with urllib.request.urlopen(
                    urllib.request.Request(server.url, data=body),
                    timeout=30,
                ) as r:
                    return r.read()
            except urllib.error.HTTPError as e:
                assert expect_error == e.code, e
                return None

        def req(user, node, ts_list):
            return protocol.SyncRequest(
                tuple(protocol.EncryptedCrdtMessage(ts, b"ct") for ts in ts_list),
                user, node, "{}",
            )

        ts = [timestamp_to_string_at(i) for i in range(4)]
        # Canonical pushes + one exact redelivery (duplicates).
        post(req("mixed-alice", "a" * 16, ts[:3]))
        post(req("mixed-alice", "a" * 16, ts[:3]))
        # Non-canonical width → singleton host-oracle reject (500).
        post(req("mixed-nc", "b" * 16,
                 ["1970-01-01T00:00:00.001Z-001-deadbeefdeadbeef"]),
             expect_error=500)
        # Poisoned engine pass → singleton retry serves it exactly once.
        poison["armed"] = True
        post(req("mixed-bob", "c" * 16, [ts[3]]))
        poison["armed"] = False
        assert poison["fired"] == 1, "poison injection never fired"
        # 503 backpressure shed.
        real_max = server.scheduler.max_queue
        server.scheduler.max_queue = 0
        post(req("mixed-shed", "d" * 16, ts[:2]), expect_error=503)
        server.scheduler.max_queue = real_max

        server.write_behind.flush()
        t = ledger_mod.totals()
        assert t[ledger_mod.WB_QUEUED] == t[ledger_mod.WB_DRAINED]
        assert t[ledger_mod.SHED_BACKPRESSURE] == 2
        assert t[ledger_mod.REJECT_INVALID] == 1
        assert t[ledger_mod.BOUNCE_NON_CANONICAL] >= 1
        # mixed-bob's row: exactly once despite the poisoned pass.
        bob = ledger_mod.ledger.owner_totals("mixed-bob")
        assert bob[ledger_mod.STORE_INSERTED] == 1
        assert bob.get(ledger_mod.STORE_DUPLICATE, 0) == 0
        violations = ledger_mod.audit(at_barrier=True)
        assert violations == [], violations
    finally:
        BatchReconciler.run_batch_wire = orig_rbw
        server.stop()


def timestamp_to_string_at(i):
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string

    return timestamp_to_string(
        Timestamp(1700000000000 + i * 1000, 0, "1234567890abcdef")
    )


@pytest.mark.slow
def test_write_behind_torture_winner_state_matches_sqlite(tmp_path):
    """The client-side half of the PR-11 invariant bar: after an
    update-heavy apply schedule (repeated cells — the shape the
    adaptive gate keeps on the cached route; a create-heavy churn
    workload legitimately streams with zero slots), the HBM winner
    slots equal SQLite's MAX(timestamp) per cell — read back from the
    device arrays via the worker's audit surface. A restart re-seeds
    the (volatile) cache lazily; the invariant must hold again after
    post-restart traffic."""
    from evolu_tpu.runtime.client import Evolu

    db_path = str(tmp_path / "client.db")
    cfg = Config(backend="tpu", min_device_batch=1)  # every apply on the cache route
    ev = Evolu(db_path=db_path, config=cfg)
    ev.update_db_schema(SCHEMA)
    try:
        ids = [ev.create("todo", {"title": f"t{i}"}) for i in range(4)]
        ev.worker.flush()
        # Update-heavy on ONE hot row, one batch per mutation (flush
        # each): repeated cells are the shape the adaptive gate keeps
        # cached (tiny batches over alternating rows read as 100%
        # churn and legitimately stream — the gate is tuned for the
        # 1M-row receive shape, not 3-cell mutations).
        hot = ids[0]
        for i in range(20):
            ev.update("todo", hot, {"title": f"edit{i}",
                                    "isCompleted": bool(i % 2)})
            ev.worker.flush()
        checked = ev.worker.verify_winner_cache()
        assert checked > 0, "the winner cache never engaged"
        ev.dispose()

        # Restart: HBM is volatile — the cache re-seeds from SQLite
        # lazily; the audit must hold on the re-seeded slots too.
        ev = Evolu(db_path=db_path, config=cfg)
        ev.update_db_schema(SCHEMA)
        for i in range(15):
            ev.update("todo", hot, {"title": f"post{i}"})
            ev.worker.flush()
        assert ev.worker.verify_winner_cache() > 0
    finally:
        ev.dispose()


def test_mesh_sharded_multi_relay_scheduler_episode(seed=90210):
    """ISSUE 12: multi-relay traffic coalescing through ONE shared
    scheduler onto the mesh-sharded engine (stable owner→device
    placement over the 8-device virtual mesh), with the PR-11
    write-behind queue on the serving path, a non-canonical hex-case
    batch (host-fold quarantine), and a non-canonical width request
    (rejected before any side effect). End state must be byte-identical
    to a SINGLE-DEVICE oracle twin replaying the same requests, and the
    clients' mesh-sharded winner caches must equal SQLite's
    MAX(timestamp) per cell, audited through the per-shard slot
    arrays."""
    import threading
    import urllib.error
    import urllib.request

    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.obs import metrics
    from evolu_tpu.ops.winner_cache import MeshShardedWinnerCache
    from evolu_tpu.parallel.mesh import MeshContext
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.scheduler import SyncScheduler
    from evolu_tpu.storage.write_behind import WriteBehindQueue
    from evolu_tpu.sync import protocol
    from evolu_tpu.parallel.mesh import create_mesh

    with _evidence("mesh-model-check", seed):
        rng = random.Random(seed)
        store = ShardedRelayStore(shards=4)
        wb = WriteBehindQueue(store)
        ctx = MeshContext()
        sched = SyncScheduler(store, write_behind=wb, mesh_ctx=ctx,
                              max_batch=8, max_wait_s=0.002)
        # Capture every request the shared scheduler serves, in
        # arrival order, for the oracle replay.
        req_log: list = []
        log_lock = threading.Lock()
        orig_submit = sched.submit

        def logged_submit(request):
            with log_lock:
                req_log.append(request)
            return orig_submit(request)

        sched.submit = logged_submit
        # TWO relays handing traffic to the ONE scheduler/device pool.
        r1 = RelayServer(store, scheduler=sched).start()
        r2 = RelayServer(store, scheduler=sched).start()
        dispatches0 = metrics.get_counter("evolu_mesh_dispatches_total")

        cfg = lambda url: Config(sync_url=url, backend="tpu",  # noqa: E731
                                 mesh_engine=True)
        a = create_evolu(SCHEMA, config=cfg(r1.url))
        b = create_evolu(SCHEMA, config=cfg(r2.url), mnemonic=a.owner.mnemonic)
        replicas = [a, b]
        try:
            for r in replicas:
                connect(r)
            assert type(a.worker._planner.cache) is MeshShardedWinnerCache
            row_ids: list = []
            for step in range(24):
                r = rng.choice(replicas)
                op = rng.random()
                if op < 0.5 or not row_ids:
                    row_ids.append(r.create("todo", {
                        "title": f"m{step}", "isCompleted": False,
                    }))
                elif op < 0.85:
                    r.update("todo", rng.choice(row_ids), {
                        "title": f"edit{step}",
                        "isCompleted": bool(rng.getrandbits(1)),
                    })
                else:
                    for x in replicas:
                        x.sync(); x.worker.flush()
            # Concurrent distinct-owner burst straight at both relays
            # (coalesces into fused sharded passes).
            BASE = 1_700_000_000_000

            def push(url, owner, node, start, n):
                msgs = tuple(
                    protocol.EncryptedCrdtMessage(
                        timestamp_to_string(
                            Timestamp(BASE + (start + i) * 1000, 0, node)),
                        b"mesh-%d" % (start + i))
                    for i in range(n))
                body = protocol.encode_sync_request(
                    protocol.SyncRequest(msgs, owner, node, "{}"))
                with urllib.request.urlopen(urllib.request.Request(
                        url, data=body,
                        headers={"Content-Type": "application/octet-stream"}),
                        timeout=60) as resp:
                    resp.read()

            threads = [
                threading.Thread(target=push, args=(
                    (r1 if i % 2 else r2).url, f"mesh-x{i}",
                    f"{i + 0x41:016x}", rng.randrange(3), 5 + i))
                for i in range(6)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            # Non-canonical hex CASE (width 46 — batchable): the engine
            # must quarantine this owner to the host fold, still store.
            node_uc = "ABCDEF0123456789"
            push(r1.url, "mesh-nc", node_uc, 0, 4)
            # Non-canonical WIDTH: singleton path, rejected with NO
            # side effect (the oracle twin never sees it either — the
            # log records it, the replay skips it identically).
            bad_ts = timestamp_to_string(Timestamp(BASE, 0, "9" * 16)) + "Z"
            body = protocol.encode_sync_request(protocol.SyncRequest(
                (protocol.EncryptedCrdtMessage(bad_ts, b"x"),),
                "mesh-bad", "9" * 16, "{}"))
            try:
                urllib.request.urlopen(urllib.request.Request(
                    r2.url, data=body,
                    headers={"Content-Type": "application/octet-stream"}),
                    timeout=60).read()
                raise AssertionError("non-canonical width must be rejected")
            except urllib.error.HTTPError as e:
                assert e.code == 500
            _converge(replicas)
            # Write-behind drain barrier, then the authoritative dump
            # (ONE shared parity-dump helper — tests/conftest.py).
            wb.flush()
            from tests.conftest import relay_store_dump as dump

            # Oracle twin: a SINGLE-DEVICE engine (1-device mesh, no
            # write-behind, per-batch LPT) replays the captured request
            # log one request per pass.
            from evolu_tpu.obs import ledger as ledger_mod

            oracle = ShardedRelayStore(shards=4)
            oeng = BatchReconciler(oracle, mesh=create_mesh(1))
            try:
                with log_lock:
                    replay = list(req_log)
                assert len(replay) > 10, "episode produced no traffic"
                with ledger_mod.quarantine():  # reference replay, not traffic
                    for req in replay:
                        try:
                            oeng.run_batch_wire([req])
                        except Exception:
                            pass  # the width-reject raises here too
                assert dump(store) == dump(oracle), (
                    "sharded multi-relay end state diverged from the "
                    "single-device oracle twin"
                )
            finally:
                oeng.close()
                oracle.close()
            # The host-fold owner really landed (quarantine stored it).
            assert store.get_merkle_tree_string("mesh-nc") != "{}"
            # Sharded passes actually ran, and the winner caches hold
            # slot == MAX(timestamp), audited via the per-shard arrays.
            assert metrics.get_counter(
                "evolu_mesh_dispatches_total") > dispatches0
            for r in replicas:
                checked = r.worker.verify_winner_cache()
                cache = r.worker._planner.cache
                assert sum(cache.shard_slot_counts()) == len(cache._slots)
                assert checked == len(cache._slots)
        finally:
            for r in replicas:
                r.dispose()
            r1.stop()
            r2.stop()
            wb.close()
            store.close()


def test_push_subscription_partition_heal_episode():
    """ISSUE 13 / ROADMAP #5 small dose: a seeded schedule drives push
    subscriptions through a network partition and heal, on the
    EVENT-LOOP connection tier. A subscriber is parked at relay B;
    writes land at relay A and reach B only via Merkle anti-entropy.
    Invariants: (1) while partitioned, B's subscriber never wakes for
    A-side writes (nothing became visible at B); (2) after heal, the
    replication-ingest wakeup fires — no wakeup missed across the
    fault; (3) wakes stay bounded by qualifying batches; (4) the
    relays converge byte-identically — push changed no state anywhere.
    """
    import json
    import threading
    import urllib.request

    from evolu_tpu.obs import metrics
    from evolu_tpu.server.replicate import ReplicationManager
    from evolu_tpu.sync import protocol
    from tests.test_replication import (
        _FaultyTransport,
        _state,
        _write,
    )
    from tests.test_push import SUB, _msgs, _sync_body  # noqa: F401

    seed = 20260813
    with _evidence("model-check-push-partition", seed):
        rng = random.Random(seed)
        n1 = "1" * 16
        stores = [RelayStore(), RelayStore()]
        faults = [_FaultyTransport(), _FaultyTransport()]
        mgrs = [
            ReplicationManager(
                s, [], replica_id=f"push-{i}", interval_s=0.1,
                debounce_s=0.02, backoff_base_s=0.05, backoff_max_s=0.3,
                http_post=f.post,
            )
            for i, (s, f) in enumerate(zip(stores, faults))
        ]
        servers = [
            RelayServer(s, replication=m,
                        connection_tier="eventloop").start()
            for s, m in zip(stores, mgrs)
        ]
        a, b = servers
        try:
            mgrs[0].add_peer(b.url)
            mgrs[1].add_peer(a.url)
            wakes = []
            stop = threading.Event()

            def subscriber():
                cursor = 0
                while not stop.is_set():
                    url = (f"{b.url}/push/poll?owner=ow&node={SUB}"
                           f"&cursor={cursor}&timeout=0.5")
                    try:
                        with urllib.request.urlopen(url, timeout=10) as r:
                            body = json.loads(r.read())
                    except Exception:  # noqa: BLE001 - teardown
                        return
                    cursor = body["cursor"]
                    if body["wake"]:
                        wakes.append(time.monotonic())

            th = threading.Thread(target=subscriber)
            th.start()
            time.sleep(0.2)

            # Phase 1 — connected: a foreign write at A must wake the
            # subscriber at B through replication ingest.
            repl_wakes0 = metrics.get_counter(
                "evolu_push_wakeups_total", reason="replication")
            _write(a.url, "ow", n1, _msgs(n1, 0, 3))
            deadline = time.time() + 15
            while not wakes:
                assert time.time() < deadline, \
                    "pre-partition replication wake never fired at B"
                time.sleep(0.02)
            assert metrics.get_counter(
                "evolu_push_wakeups_total",
                reason="replication") > repl_wakes0

            # Phase 2 — partition both directions, keep writing at A
            # (mixed authors, seeded). B's subscriber must stay silent:
            # nothing became visible AT B.
            faults[0].block(b.url)
            faults[1].block(a.url)
            time.sleep(0.2)
            n_wakes_at_partition = len(wakes)
            qualifying = 0
            base = 100
            for _step in range(rng.randint(3, 6)):
                author = rng.choice([n1, SUB])
                n = rng.randint(1, 3)
                _write(a.url, "ow", author, _msgs(author, base, n))
                base += n
                qualifying += 1 if author != SUB else 0
            time.sleep(0.6)  # several gossip intervals
            assert len(wakes) == n_wakes_at_partition, \
                "subscriber at B woke during the partition"

            # Phase 3 — heal: the pulled rows must wake B's subscriber
            # (they can never arrive as a local POST there), and both
            # relays converge byte-identically.
            faults[0].heal()
            faults[1].heal()
            mgrs[0].hint()
            mgrs[1].hint()
            deadline = time.time() + 20
            while len(wakes) == n_wakes_at_partition:
                assert time.time() < deadline, \
                    "post-heal replication wake never fired (wakeup missed)"
                time.sleep(0.02)
            deadline = time.time() + 20
            while _state(stores[0]) != _state(stores[1]):
                assert time.time() < deadline, "relays did not converge"
                time.sleep(0.05)
            sa = _state(stores[0])
            assert sa == _state(stores[1])
            assert sum(len(rows) for _t, rows in sa.values()) == base - 100 + 3
            # Spurious bound: the subscriber woke at most once per
            # qualifying foreign batch (+1 for the heal's coalesced
            # pull — replication may deliver the backlog as one batch).
            assert len(wakes) <= 1 + qualifying + 1
        finally:
            stop.set()
            for s in servers:
                s.stop()
            th.join(timeout=5)


def test_scoped_partial_replication_episode():
    """ISSUE 18 satellite: one seeded adversarial episode through the
    partial-replication plane — a FULL and a SCOPED device of one
    owner, homed at DIFFERENT relays that gossip via anti-entropy
    replication, under regressing/stuttering HLC clocks, a relay-level
    partition and heal, a NON-CANONICAL batch bouncing to the host
    oracle mid-stream, and a mid-stream scope escalation. Invariants:
    the two devices' __message logs converge byte-identically (the
    scoped device defers MATERIALIZATION, never history); the scoped
    device's in-scope table is byte-identical to the full device's;
    the out-of-scope table stays empty with a COUNTER-EXACT deferred
    frontier; after widening, the scoped device is byte-identical
    everywhere, including rows written after the escalation; and the
    conservation ledger balances at episode end (_evidence audits).

    The reference's livelock guard (repeated identical merkle diff) CAN
    fire transiently here — frozen adversarial clocks cluster rows into
    one minute while relay gossip keeps landing foreign rows into that
    same minute between a device's rounds — so transient SyncError is
    tolerated (each next sync starts a fresh chain), matching the other
    replicating-relay episodes above; any OTHER surfaced error fails
    the episode."""
    with _evidence("model-check-scope", 20260807):
        _run_scoped_partial_replication_episode()


def _run_scoped_partial_replication_episode():
    from evolu_tpu.core.merkle import apply_prefix_xors, minute_deltas_host
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.core.types import CrdtMessage, SyncError
    from evolu_tpu.obs import metrics
    from evolu_tpu.runtime import messages as wmsg
    from evolu_tpu.server.replicate import ReplicationManager
    from evolu_tpu.sync.scope import ScopeDeferred, SyncScope  # noqa: F401
    from tests.test_replication import _FaultyTransport, _state

    seed = 20260807
    rng = random.Random(seed)
    base = int(time.time() * 1000)

    def adversarial_now(sub_seed):
        """Same hostile envelope as the fleet episode above: 40%
        frozen, 20% bounded regression, else small advances."""
        r = random.Random(sub_seed)
        state = {"t": base}

        def now():
            roll = r.random()
            if roll < 0.4:
                pass  # stutter
            elif roll < 0.6:
                state["t"] = max(base - 20_000,
                                 state["t"] - r.randrange(0, 10_000))
            else:
                state["t"] += r.randrange(1, 400)
            return state["t"]

        return now

    stores = [RelayStore(), RelayStore()]
    faults = [_FaultyTransport(), _FaultyTransport()]
    mgrs = [
        ReplicationManager(
            s, [], replica_id=f"scope-{i}", interval_s=0.1,
            debounce_s=0.02, backoff_base_s=0.05, backoff_max_s=0.3,
            http_post=f.post,
        )
        for i, (s, f) in enumerate(zip(stores, faults))
    ]
    servers = [RelayServer(s, replication=m).start()
               for s, m in zip(stores, mgrs)]
    a, b = servers
    replicas = []
    try:
        mgrs[0].add_peer(b.url)
        mgrs[1].add_peer(a.url)
        full = create_evolu(SCHEMA, config=Config(sync_url=a.url,
                                                  backend="tpu"))
        thin = create_evolu(
            SCHEMA, mnemonic=full.owner.mnemonic,
            config=Config(sync_url=b.url, backend="cpu",
                          sync_scope=SyncScope(tables=("todo",))))
        replicas = [full, thin]
        errors = []
        for i, r in enumerate(replicas):
            r.worker.now = adversarial_now(seed + i)
            connect(r)
            r.subscribe_error(errors.append)

        def step(r, allow_category):
            tables = ["todo", "todo", "todoCategory"] if allow_category \
                else ["todo"]
            t = rng.choice(tables)
            if t == "todo":
                r.create("todo", {"title": f"t{rng.randrange(10**6)}",
                                  "isCompleted": False})
            else:
                r.create("todoCategory",
                         {"name": f"c{rng.randrange(10**6)}"})
            r.worker.flush()
            if rng.random() < 0.4:
                r.sync()
                r.worker.flush()

        # Phase 1 — connected: mixed writes. The full device writes
        # both tables; the scoped device writes only its slice.
        for _ in range(14):
            step(full, True)
            step(thin, False)

        # Mid-stream NON-CANONICAL batch (uppercase node hex) injected
        # at the full device for the IN-SCOPE table: the apply must
        # route to the host oracle (r5 contract) on every replica it
        # reaches via anti-entropy.
        bounces0 = metrics.get_counter("evolu_merge_host_fallbacks_total")
        full._transport.flush()
        full.worker.flush()
        nc = tuple(
            CrdtMessage(
                (lambda s: s[:30] + s[30:].upper())(timestamp_to_string(
                    Timestamp(base + 1000 + i, 0, "00000000000000ab"))),
                "todo", f"ncrow{i}", "title", f"nc{i}")
            for i in range(3)
        )
        from evolu_tpu.storage.clock import read_clock
        local = read_clock(full.db).merkle_tree
        deltas, _ = minute_deltas_host(m.timestamp for m in nc)
        full.receive(nc, merkle_tree_to_string(
            apply_prefix_xors(dict(local), deltas)))
        full.worker.flush()
        assert metrics.get_counter(
            "evolu_merge_host_fallbacks_total") > bounces0

        # Phase 2 — partition the relay gossip both directions; the
        # devices keep writing against their OWN relay.
        faults[0].block(b.url)
        faults[1].block(a.url)
        for _ in range(8):
            step(full, True)
            step(thin, False)

        # Phase 3 — heal, then converge: relay gossip AND both
        # devices' sync rounds, until the two LOGS are byte-identical.
        faults[0].heal()
        faults[1].heal()
        mgrs[0].hint()
        mgrs[1].hint()

        def log(r):
            return r.db.exec(
                'SELECT * FROM "__message" ORDER BY "timestamp"')

        deadline = time.time() + 60
        while True:
            for r in replicas:
                r.sync()
                r.worker.flush()
            if log(full) == log(thin) and \
                    _state(stores[0]) == _state(stores[1]):
                break
            assert time.time() < deadline, \
                "logs/relays did not converge across the scope boundary"
            time.sleep(0.05)
        for r in replicas:
            r._transport.flush()
            r.worker.flush()
        assert not [e for e in errors if not isinstance(e, SyncError)], \
            "non-livelock error surfaced"

        # Within-slice byte-identity: the scoped device's in-scope
        # table equals the full device's, non-canonical rows included.
        todo_full = full.db.exec('SELECT * FROM "todo" ORDER BY "id"')
        todo_thin = thin.db.exec('SELECT * FROM "todo" ORDER BY "id"')
        assert todo_full == todo_thin
        assert any(r[0].startswith("ncrow")
                   for r in thin.db.exec('SELECT "id" FROM "todo"'))
        # Out-of-scope: zero materialized rows, counter-EXACT frontier
        # (the thin device authored no todoCategory rows, so every one
        # in its log was deferred — and redeliveries must not inflate).
        assert thin.db.exec('SELECT * FROM "todoCategory"') == []
        n_cat = thin.db.exec_sql_query(
            'SELECT COUNT(*) AS n FROM "__message" WHERE "table" = ?',
            ("todoCategory",))[0]["n"]
        assert n_cat > 0, "episode never exercised the deferred leg"
        frontier = thin.db.exec_sql_query(
            'SELECT "rows" FROM "__scope_deferred" WHERE "table" = ?',
            ("todoCategory",))
        assert frontier and frontier[0]["rows"] == n_cat

        # Mid-stream escalation: widen to full, then keep writing.
        thin.worker.post(wmsg.WidenSyncScope(full=True))
        thin.worker.flush()
        assert thin.db.exec_sql_query(
            'SELECT * FROM "__scope_deferred"') == []
        for _ in range(4):
            step(full, True)
        deadline = time.time() + 60
        while True:
            for r in replicas:
                r.sync()
                r.worker.flush()
            if log(full) == log(thin):
                break
            assert time.time() < deadline, \
                "post-escalation convergence failed"
            time.sleep(0.05)
        for r in replicas:
            r._transport.flush()
            r.worker.flush()
        # Byte-identical EVERYWHERE now — the re-materialized table
        # equals the always-materialized one, new writes included.
        assert full.db.exec('SELECT * FROM "todoCategory" ORDER BY "id"') \
            == thin.db.exec('SELECT * FROM "todoCategory" ORDER BY "id"')
        assert full.db.exec('SELECT * FROM "todo" ORDER BY "id"') \
            == thin.db.exec('SELECT * FROM "todo" ORDER BY "id"')
        assert not [e for e in errors if not isinstance(e, SyncError)], \
            "non-livelock error surfaced"
    finally:
        for r in replicas:
            r.dispose()
        for s in servers:
            s.stop()


def test_tensor_crdt_partition_heal_adversarial_clocks_episode():
    """ISSUE 20 satellite (ROADMAP #5 dose): tensor-valued columns
    (sum / mean-by-count / max monoids with overwrite∘delta semidirect
    composition) under regressing/stuttering HLC clocks through a
    2-relay fleet with a partition/heal cycle and a mid-stream
    non-canonical host-bounce. Asserts ELEMENT-EXACT tensor
    convergence against the pure-numpy replay oracle, counter
    exactness for the LWW/counter traffic riding along, winner-cache
    == MAX(timestamp) on the device replica, and (via _evidence)
    `ledger.audit()` returning zero violated equations."""
    with _evidence("model-check-tensor-crdt", 20260807):
        _run_tensor_crdt_episode()


def _run_tensor_crdt_episode():
    import numpy as np

    from evolu_tpu.core import crdt_tensor as tz
    from evolu_tpu.core import crdt_types as ct
    from evolu_tpu.core.merkle import create_initial_merkle_tree
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.obs import metrics
    from evolu_tpu.utils.config import FleetConfig

    seed = 20260807
    rng = random.Random(seed)
    base = int(time.time() * 1000)

    def adversarial_now(sub_seed):
        r = random.Random(sub_seed)
        state = {"t": base}

        def now():
            roll = r.random()
            if roll < 0.4:
                pass  # stutter: frozen clock
            elif roll < 0.6:
                state["t"] = max(base - 20_000,
                                 state["t"] - r.randrange(0, 10_000))
            else:
                state["t"] += r.randrange(1, 400)
            return state["t"]

        return now

    tensor_cols = {"weights": "tensor:sum:f32:4",
                   "avg": "tensor:mean:f32:2",
                   "peak": "tensor:max:f32:3"}
    schema = {"models": ("name", "clicks:counter", "tags:awset",
                         "steps:list") + tuple(
                             f"{c}:{t}" for c, t in tensor_cols.items())}
    cfgs = {c: tz.parse_tensor_type(t) for c, t in tensor_cols.items()}
    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    fleet_cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                            version=1)
    a.enable_fleet(fleet_cfg)
    b.enable_fleet(fleet_cfg)
    replicas = []
    errors = []
    try:
        r1 = create_evolu(schema, config=Config(sync_url=a.url, backend="tpu"))
        r2 = create_evolu(schema, config=Config(sync_url=b.url, backend="cpu"),
                          mnemonic=r1.owner.mnemonic)
        replicas = [r1, r2]
        for i, r in enumerate(replicas):
            r.worker.now = adversarial_now(seed + i)
            r.subscribe_error(errors.append)
            connect(r)

        # Phase 1 (online): shared rows + overwrite bases, kept in sync.
        rows = []
        expected_sum = {}
        for r in replicas:
            rid = r.create("models", {"name": f"m-{id(r)}"})
            r.worker.flush()
            rows.append(rid)
            expected_sum[rid] = 0
        r1.tensor_set("models", rows[0], "weights", [10.0, 20.0, -5.0, 0.5])
        r1.tensor_set("models", rows[0], "avg", [100.0, 200.0], count=2)
        r1.worker.flush()
        _converge(replicas)
        assert metrics.get_gauge(
            "evolu_crdt_tensor_capability_negotiated") == 1

        def random_step(r, step, online):
            roll = rng.random()
            rid = rng.choice(rows)
            if roll < 0.30:
                col = rng.choice(("weights", "avg", "peak"))
                cfg = cfgs[col]
                vals = [rng.uniform(-25, 25) for _ in range(cfg.size)]
                cnt = rng.randrange(1, 6) if cfg.monoid == "mean" else 1
                r.tensor_delta("models", rid, col, vals, count=cnt)
            elif roll < 0.38:
                # A mid-stream overwrite: resets the fold base, later
                # deltas reapply (the semidirect composition under fire).
                col = rng.choice(("weights", "peak"))
                cfg = cfgs[col]
                r.tensor_set("models", rid, col,
                             [rng.uniform(-25, 25) for _ in range(cfg.size)])
            elif roll < 0.58:
                d = rng.randrange(-50, 51)
                r.increment("models", rid, "clicks", d)
                expected_sum[rid] += d
            elif roll < 0.72:
                r.set_add("models", rid, "tags", rng.choice("abcd"))
            elif roll < 0.80:
                r.set_remove("models", rid, "tags", rng.choice("abcd"))
            elif roll < 0.90:
                r.list_append("models", rid, "steps", f"s{step}")
            else:
                r.update("models", rid, {"name": f"n{step}"})
            r.worker.flush()
            if online and rng.random() < 0.5:
                s = rng.choice(replicas)
                s.sync()
                s.worker.flush()

        for step in range(40):  # online phase
            random_step(rng.choice(replicas), step, online=True)
        _converge(replicas)

        # Phase 2 (PARTITION): no sync rounds; both sides mutate the
        # SAME tensor cells concurrently, including competing overwrites.
        for step in range(40, 72):
            random_step(replicas[step % 2], step, online=False)

        # Mid-partition hostile case: a NON-CANONICAL (uppercase node
        # hex) remote batch — the LWW cell bounces the device planner
        # to the host oracle, and a tensor op in the SAME batch proves
        # the tensor leg is canonicalization-blind (host raw-string
        # ordering; the device never sees a timestamp). Injected into
        # BOTH replicas so the merged histories stay identical.
        bounces_before = metrics.get_counter("evolu_merge_host_fallbacks_total")
        empty_tree = merkle_tree_to_string(create_initial_merkle_tree())

        def nc_ts(i):
            s = timestamp_to_string(
                Timestamp(base + 5000 + i, i, "00000000000000ab"))
            return s[:30] + s[30:].upper()

        hostile = tuple(
            [CrdtMessage(nc_ts(j), "models", "remrow", "name", f"h{j}")
             for j in range(3)]
            + [CrdtMessage(nc_ts(7), "models", "remrow", "weights",
                           tz.tensor_delta_value(
                               cfgs["weights"], [1.0, 2.0, 3.0, 4.0]))])
        for r in replicas:
            r.receive(hostile, empty_tree)
            r.worker.flush()
        assert metrics.get_counter(
            "evolu_merge_host_fallbacks_total") > bounces_before

        # Phase 3 (HEAL): sync rounds resume.
        _converge(replicas)
        for r in replicas:
            r._transport.flush()
            r.worker.flush()

        from evolu_tpu.core.types import SyncError
        real = [e for e in errors if not isinstance(e, SyncError)]
        assert not real, real

        dumps = []
        for r in replicas:
            dumps.append((
                r.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'),
                r.db.exec('SELECT * FROM "models" ORDER BY "id"'),
                r.db.exec('SELECT * FROM "__crdt_tensor" ORDER BY "tag","column"'),
                r.db.exec('SELECT * FROM "__crdt_counter" ORDER BY "row","column"'),
                r.db.exec('SELECT * FROM "__crdt_set" ORDER BY "tag"'),
                r.db.exec('SELECT * FROM "__crdt_list" ORDER BY "tag"'),
            ))
        assert dumps[0] == dumps[1], "state diverged after partition/heal"

        # ELEMENT-EXACT tensor convergence: every materialized tensor
        # cell equals the pure-numpy replay of the merged log, bit for
        # bit (the any-permutation acceptance bar, end to end).
        log_rows = r1.db.exec_sql_query(
            'SELECT "timestamp", "table", "row", "column", "value" '
            'FROM "__message" WHERE "table" = ?', ("models",))
        types = {("models", c): t for c, t in tensor_cols.items()}
        oracle = tz.replay_log(types, [
            CrdtMessage(r["timestamp"], r["table"], r["row"], r["column"],
                        r["value"]) for r in log_rows])
        assert oracle, "episode produced no tensor traffic"
        folded_cells = 0
        for (table, rid, col), expected in oracle.items():
            for r in replicas:
                got = tz.tensor_state(r.db, table, rid, col)
                assert got is not None and got.tobytes() == expected, \
                    (rid, col)
            folded_cells += 1
        assert folded_cells >= 4  # the schedule exercised several cells
        # The non-canonical tensor delta folded into its own cell.
        assert np.array_equal(
            tz.tensor_state(r1.db, "models", "remrow", "weights"),
            np.asarray([1.0, 2.0, 3.0, 4.0], np.float32))

        # Counter EXACTNESS rides along undisturbed.
        for rid, total in expected_sum.items():
            got = r1.db.exec_sql_query(
                'SELECT "clicks" FROM "models" WHERE "id" = ?', (rid,)
            )[0]["clicks"]
            assert got == total, (rid, got, total)

        # Fold integrity: rebuilding from the full log is a no-op.
        schema_r1 = ct.load_schema(r1.db)
        before = r1.db.exec('SELECT * FROM "__crdt_tensor" ORDER BY "tag"')
        ct.rebuild_state(r1.db, schema_r1)
        assert r1.db.exec(
            'SELECT * FROM "__crdt_tensor" ORDER BY "tag"') == before

        # Winner-cache == MAX(timestamp) on the device replica.
        cache = r1.worker._planner.cache
        w1 = np.asarray(cache._w1)
        w2 = np.asarray(cache._w2)
        for (table, rr, col), slot in cache._slots.items():
            got = r1.db.exec_sql_query(
                'SELECT MAX("timestamp") AS m FROM "__message" '
                'WHERE "table" = ? AND "row" = ? AND "column" = ?',
                (table, rr, col))[0]["m"]
            k1, k2 = int(w1[slot]), int(w2[slot])
            if k1 == 0 and k2 == 0:
                assert got is None, (table, rr, col)
                continue
            cached_ts = timestamp_to_string(
                Timestamp(k1 >> 16, k1 & 0xFFFF, f"{k2:016x}"))
            assert cached_ts == got, (table, rr, col)
    finally:
        for r in replicas:
            r.dispose()
        a.stop()
        b.stop()
