"""The DCN leg, actually executed: a REAL 2-process jax.distributed
cluster (Gloo collectives across processes — the CPU stand-in for DCN)
running the owner-fleet reconcile over the global mesh.

Round-1 review: "`initialize_multihost` has never executed its actual
purpose". Here it does — two OS processes join one cluster (4 virtual
devices each → an 8-device global mesh), every process feeds its
addressable shards, the XOR digest all-reduces across processes, and
each process's local shard outputs cover exactly its owners' messages
(tests/_multihost_worker.py carries the assertions)."""

import functools
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).resolve().parent / "_multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Minimal cross-process collective: 2 OS processes join one
# jax.distributed cluster and psum across it. sys.argv under `-c` is
# ["-c", pid, nproc, port].
_PROBE = """\
import sys
import jax
import jax.numpy as jnp

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.ones((jax.local_device_count(), 1))
)
assert float(out[0, 0]) == jax.device_count(), out
print("COLLECTIVE-OK", flush=True)
"""


@functools.lru_cache(maxsize=1)
def _multiprocess_cpu_collectives_failure() -> str:
    """'' when a 2-OS-process jax.distributed CPU cluster can execute a
    cross-process collective here; otherwise the failure's last output
    line. Some jaxlib CPU builds reject this shape outright
    ("Multiprocess computations aren't implemented on the CPU
    backend") — there the CAPABILITY is absent, and the cluster tests
    must skip rather than fail: they exercise the DCN leg, not the
    local build's backend matrix."""
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                out = "probe timed out"
            outs.append(out or "")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if all(p.returncode == 0 and "COLLECTIVE-OK" in o for p, o in zip(procs, outs)):
        return ""
    lines = [l for l in "\n".join(outs).splitlines() if l.strip()]
    return lines[-1] if lines else "no probe output"


def _require_multiprocess_collectives() -> None:
    failure = _multiprocess_cpu_collectives_failure()
    if failure:
        pytest.skip(
            "multiprocess CPU collectives unavailable in this jax build "
            f"(probe: {failure})"
        )


def test_pod_server_across_two_processes(tmp_path):
    """VERDICT r3 #3: the WHOLE server — BatchReconciler semantics +
    ShardedRelayStore — spanning a 2-process jax.distributed cluster
    (engine.reconcile_pod): storage partitioned by the stable owner
    hash, the device Merkle leg one SPMD dispatch over the global
    8-device mesh, digest all-reduced pod-wide. Every request must be
    answered by exactly one process, and the union of responses must
    be BYTE-equal (encoded protobuf) to the single-process
    BatchReconciler reference for both a push round and a cold-sync
    round (full-history pull)."""
    _require_multiprocess_collectives()
    import base64

    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.relay import ShardedRelayStore
    from evolu_tpu.sync.protocol import encode_sync_response
    from tests._pod_requests import build_batches

    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    worker = Path(__file__).resolve().parent / "_pod_worker.py"
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i}: OK" in out, out

    # Single-process reference over the same batches.
    push, cold = build_batches()
    ref_store = ShardedRelayStore(str(tmp_path / "ref"), shards=4)
    eng = BatchReconciler(ref_store)
    try:
        ref = {
            "push": eng.reconcile(push),
            "replay": eng.reconcile(push),  # store-duplicate round
            "cold": eng.reconcile(cold),
        }
    finally:
        eng.close(), ref_store.close()

    got: dict = {"push": {}, "replay": {}, "cold": {}}
    digests: dict = {"push": [], "replay": [], "cold": []}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESP "):
                _, rnd, i, b64 = line.split()
                assert int(i) not in got[rnd], f"request {i} answered twice"
                got[rnd][int(i)] = base64.b64decode(b64)
            elif line.startswith("DIGEST "):
                _, rnd, _pid, dg = line.split()
                digests[rnd].append(dg)
    for rnd, reqs in (("push", push), ("replay", push), ("cold", cold)):
        assert sorted(got[rnd]) == list(range(len(reqs))), (
            f"{rnd}: every request answered exactly once"
        )
        for i, resp in enumerate(ref[rnd]):
            assert got[rnd][i] == encode_sync_response(resp), (
                f"{rnd} request {i}: pod response != single-process reference"
            )
        assert len(set(digests[rnd])) == 1, f"{rnd}: digests diverged {digests[rnd]}"


def test_pod_single_process_quarantines_non_canonical_owner(tmp_path):
    """An owner whose batch carries non-canonical hex case must take
    the host fold on its owning process (device hashing re-renders
    canonical case and would diverge) — responses still byte-equal to
    the single-process engine, which quarantines identically."""
    from evolu_tpu.server.engine import BatchReconciler, reconcile_pod
    from evolu_tpu.server.relay import ShardedRelayStore
    from evolu_tpu.sync.protocol import (
        EncryptedCrdtMessage,
        SyncRequest,
        encode_sync_response,
    )
    from evolu_tpu.core.merkle import (
        apply_prefix_xors,
        merkle_tree_to_string,
        minute_deltas_host,
    )
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.parallel.mesh import create_mesh

    base = 1_700_000_000_000
    reqs = []
    for o, canonical in ((0, True), (1, False), (2, True)):
        node = f"{0xABCDEF1234567890 + o:016x}"  # hex LETTERS present
        ts = [
            timestamp_to_string(Timestamp(base + (o * 7 + i) * 60_000, i, node))
            for i in range(4)
        ]
        if not canonical:
            # Uppercase NODE hex: parses fine, but the reference hashes
            # the verbatim string — the canonical-case quarantine trigger.
            ts2 = [t[:25] + t[25:].replace("a", "A").replace("b", "B") for t in ts]
            assert ts2 != ts, "transform must actually change the strings"
            ts = ts2
        msgs = tuple(EncryptedCrdtMessage(t, b"ct-%d" % o) for t in ts)
        deltas, _ = minute_deltas_host(iter(ts))
        tree = merkle_tree_to_string(apply_prefix_xors({}, deltas))
        reqs.append(SyncRequest(msgs, f"owner{o}", "f" * 16, tree))

    mesh = create_mesh()
    pod_store = ShardedRelayStore(str(tmp_path / "pod"), shards=2)
    wire_store = ShardedRelayStore(str(tmp_path / "wire"), shards=2)
    ref_store = ShardedRelayStore(str(tmp_path / "ref"), shards=2)
    eng = BatchReconciler(ref_store)
    try:
        pod_resp, _digest = reconcile_pod(mesh, pod_store, tuple(reqs))
        ref_resp = eng.reconcile(tuple(reqs))
        for i, (p, r) in enumerate(zip(pod_resp, ref_resp)):
            assert p is not None
            assert encode_sync_response(p) == encode_sync_response(r), f"req {i}"
        # The non-canonical owner's tree really did come from the host
        # fold: it must match an independent host recompute verbatim.
        host_deltas, _ = minute_deltas_host(m.timestamp for m in reqs[1].messages)
        want = merkle_tree_to_string(apply_prefix_xors({}, host_deltas))
        assert pod_resp[1].merkle_tree == want
        # r5 pod serve path: wire=True must emit the exact encodings of
        # the object-mode responses (fresh store — same ingest inputs).
        wire_resp, _d = reconcile_pod(mesh, wire_store, tuple(reqs), wire=True)
        for i, (w, r) in enumerate(zip(wire_resp, ref_resp)):
            assert w == encode_sync_response(r), f"wire req {i}"
    finally:
        eng.close(), pod_store.close(), wire_store.close(), ref_store.close()


def test_two_process_cluster_reconcile():
    _require_multiprocess_collectives()
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i}:" in out and "OK" in out, out
    # Both processes agree on the whole-batch digest.
    d0 = [l for l in outs[0].splitlines() if "digest=" in l][0].split("digest=")[1].split()[0]
    d1 = [l for l in outs[1].splitlines() if "digest=" in l][0].split("digest=")[1].split()[0]
    assert d0 == d1
