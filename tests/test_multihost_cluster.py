"""The DCN leg, actually executed: a REAL 2-process jax.distributed
cluster (Gloo collectives across processes — the CPU stand-in for DCN)
running the owner-fleet reconcile over the global mesh.

Round-1 review: "`initialize_multihost` has never executed its actual
purpose". Here it does — two OS processes join one cluster (4 virtual
devices each → an 8-device global mesh), every process feeds its
addressable shards, the XOR digest all-reduces across processes, and
each process's local shard outputs cover exactly its owners' messages
(tests/_multihost_worker.py carries the assertions)."""

import os
import socket
import subprocess
import sys
from pathlib import Path

WORKER = Path(__file__).resolve().parent / "_multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cluster_reconcile():
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i}:" in out and "OK" in out, out
    # Both processes agree on the whole-batch digest.
    d0 = [l for l in outs[0].splitlines() if "digest=" in l][0].split("digest=")[1].split()[0]
    d1 = [l for l in outs[1].splitlines() if "digest=" in l][0].split("digest=")[1].split()[0]
    assert d0 == d1
