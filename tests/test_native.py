"""C++ SQLite host layer: build, interface parity, byte-identical end
state vs the Python backend (SURVEY.md §2.14 "real SQLite via the C API
behind a C++ host layer" + the byte-identical north star)."""

import random

import pytest

from evolu_tpu.core.ids import create_node_id
from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.storage.apply import apply_messages, apply_messages_sequential
from evolu_tpu.storage.native import (
    CppSqliteDatabase,
    native_available,
    open_database,
)
from evolu_tpu.storage.schema import init_db_model
from evolu_tpu.storage.sqlite import PySqliteDatabase

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native host library unavailable"
)


def ts(millis, counter=0, node=None):
    return timestamp_to_string(Timestamp(millis, counter, node or "a" * 16))


def make_messages(n=200, seed=1):
    rng = random.Random(seed)
    nodes = [create_node_id() for _ in range(4)]
    tables = ["todo", "todoCategory"]
    msgs = []
    for i in range(n):
        table = rng.choice(tables)
        row = f"row{rng.randrange(20)}"
        col = rng.choice(["title", "isCompleted", "categoryId"])
        value = rng.choice(["x", "y", 1, 0, None, 3.5, f"v{i}"])
        t = Timestamp(1_700_000_000_000 + rng.randrange(0, 120_000), rng.randrange(4), rng.choice(nodes))
        msgs.append(CrdtMessage(timestamp_to_string(t), table, row, col, value))
    return msgs


def bootstrap(db):
    init_db_model(db, mnemonic=None)
    for table in ("todo", "todoCategory"):
        db.exec(
            f'CREATE TABLE IF NOT EXISTS "{table}" ('
            '"id" TEXT PRIMARY KEY, "title" BLOB, "isCompleted" BLOB, "categoryId" BLOB)'
        )


def dump(db):
    rows = {}
    for table in ("todo", "todoCategory", "__message"):
        rows[table] = db.exec(f'SELECT * FROM "{table}" ORDER BY 1, 2')
    return rows


def test_basic_interface_parity():
    cpp = CppSqliteDatabase()
    py = PySqliteDatabase()
    for db in (cpp, py):
        db.exec('CREATE TABLE "t" ("a", "b")')
        db.run('INSERT INTO "t" VALUES (?, ?)', (1, "x"))
        db.run_many('INSERT INTO "t" VALUES (?, ?)', [(2, None), (3, 2.5), (4, b"\x00\xff")])
    assert cpp.exec('SELECT * FROM "t"') == py.exec('SELECT * FROM "t"')
    assert cpp.exec_sql_query('SELECT "a", "b" FROM "t" WHERE "a" > ?', (1,)) == (
        py.exec_sql_query('SELECT "a", "b" FROM "t" WHERE "a" > ?', (1,))
    )
    assert cpp.run('UPDATE "t" SET "b" = ? WHERE "a" < ?', ("z", 3)) == 2
    cpp.close()
    py.close()


def test_transaction_rollback_and_reentrancy():
    db = CppSqliteDatabase()
    db.exec('CREATE TABLE "t" ("x")')
    with pytest.raises(RuntimeError):
        with db.transaction():
            db.run('INSERT INTO "t" VALUES (1)')
            with db.transaction():  # joins the outer txn
                db.run('INSERT INTO "t" VALUES (2)')
            raise RuntimeError("boom")
    assert db.exec('SELECT COUNT(*) FROM "t"') == [(0,)]
    with db.transaction():
        db.run('INSERT INTO "t" VALUES (3)')
    assert db.exec('SELECT * FROM "t"') == [(3,)]
    db.close()


def test_error_surface():
    from evolu_tpu.core.types import UnknownError

    db = CppSqliteDatabase()
    with pytest.raises(UnknownError):
        db.exec("SELECT nonsense FROM nowhere")
    db.close()


def test_apply_sequential_matches_python_backend():
    msgs = make_messages()
    cpp, py = CppSqliteDatabase(), PySqliteDatabase()
    bootstrap(cpp), bootstrap(py)
    tree_c, tree_p = {}, {}
    with cpp.transaction():
        tree_c = apply_messages_sequential(cpp, tree_c, msgs)
    with py.transaction():
        tree_p = apply_messages_sequential(py, tree_p, msgs)
    assert dump(cpp) == dump(py)
    assert merkle_tree_to_string(tree_c) == merkle_tree_to_string(tree_p)
    cpp.close(), py.close()


def test_apply_batched_matches_python_backend():
    msgs = make_messages(seed=7)
    cpp, py = CppSqliteDatabase(), PySqliteDatabase()
    bootstrap(cpp), bootstrap(py)
    tree_c = apply_messages(cpp, {}, msgs)
    tree_p = apply_messages(py, {}, msgs)
    assert dump(cpp) == dump(py)
    assert merkle_tree_to_string(tree_c) == merkle_tree_to_string(tree_p)
    # Re-applying the same batch is idempotent on state.
    state = dump(cpp)
    apply_messages(cpp, tree_c, msgs)
    assert dump(cpp) == state
    cpp.close(), py.close()


def test_fetch_winners_and_relay_insert():
    db = CppSqliteDatabase()
    bootstrap(db)
    msgs = [
        CrdtMessage(ts(1_700_000_000_000), "todo", "r1", "title", "a"),
        CrdtMessage(ts(1_700_000_060_000), "todo", "r1", "title", "b"),
        CrdtMessage(ts(1_700_000_120_000), "todo", "r2", "title", "c"),
    ]
    with db.transaction():
        apply_messages_sequential(db, {}, msgs)
    winners = db.fetch_winners(
        [("todo", "r1", "title"), ("todo", "r2", "title"), ("todo", "rX", "title")]
    )
    assert winners == [ts(1_700_000_060_000), ts(1_700_000_120_000), None]

    db.exec(
        'CREATE TABLE "message" ("timestamp" TEXT, "userId" TEXT, "content" BLOB, '
        'PRIMARY KEY ("timestamp", "userId"))'
    )
    rows = [(ts(1), "u1", b"\x01\x02"), (ts(2), "u1", b"\x03"), (ts(1), "u1", b"dup")]
    flags = db.relay_insert(rows)
    assert flags == [True, True, False]
    assert db.exec('SELECT COUNT(*) FROM "message"') == [(2,)]
    db.close()


def test_open_database_auto_prefers_native():
    db = open_database(backend="auto")
    assert isinstance(db, CppSqliteDatabase)
    db.close()


def test_end_to_end_client_on_native_backend(tmp_path):
    from evolu_tpu.runtime.client import Evolu

    e = Evolu(db_path=str(tmp_path / "n.db"), backend="native")
    try:
        assert isinstance(e.db, CppSqliteDatabase)
        e.update_db_schema({"todo": ("title",)})
        rid = e.create("todo", {"title": "native"})
        e.worker.flush()
        rows = e.query_once('SELECT "id", "title" FROM "todo"')
        assert rows == [{"id": rid, "title": "native"}]
    finally:
        e.dispose()


def test_closed_database_raises_not_crashes():
    from evolu_tpu.core.types import UnknownError

    db = CppSqliteDatabase()
    db.close()
    with pytest.raises(UnknownError, match="closed"):
        db.exec("SELECT 1")
    with pytest.raises(UnknownError, match="closed"):
        with db.transaction():
            pass
    db.close()  # double close is a no-op


def test_multi_statement_exec_raises_like_python():
    db = CppSqliteDatabase()
    db.exec('CREATE TABLE "a" ("x")')
    db.exec('CREATE TABLE "b" ("x")')
    with pytest.raises(Exception, match="one statement"):
        db.exec('DELETE FROM "a"; DELETE FROM "b"')
    # trailing whitespace/semicolons are fine
    assert db.exec("SELECT 1 ;  ") == [(1,)]
    db.close()


def test_duplicate_timestamp_distinct_values_backend_parity():
    # A hostile peer sends two messages with the SAME timestamp for the
    # same cell but different values: both backends must end identically.
    t = ts(1_700_000_000_000)
    msgs = [
        CrdtMessage(t, "todo", "r1", "title", "A"),
        CrdtMessage(t, "todo", "r1", "title", "B"),
    ]
    cpp, py = CppSqliteDatabase(), PySqliteDatabase()
    bootstrap(cpp), bootstrap(py)
    apply_messages(cpp, {}, msgs)
    apply_messages(py, {}, msgs)
    assert dump(cpp) == dump(py)
    cpp.close(), py.close()


def test_run_on_closed_database_raises():
    from evolu_tpu.core.types import UnknownError

    db = CppSqliteDatabase()
    db.close()
    with pytest.raises(UnknownError, match="closed"):
        db.run("SELECT 1")


def test_trailing_comments_accepted_like_python():
    db = CppSqliteDatabase()
    assert db.exec("SELECT 1; -- done") == [(1,)]
    assert db.exec("SELECT 2; /* trailing\n block */ ;") == [(2,)]
    db.close()


def test_embedded_nul_in_wire_fields_backend_parity():
    """Hostile wire data: table/row/column strings carrying embedded
    NUL bytes must produce byte-identical __message rows on both
    backends (the packed C path binds with explicit byte lengths; a
    NUL-terminated bind would silently truncate). A NUL inside an
    UPSERTED identifier aborts on both backends instead."""
    from evolu_tpu.core.types import UnknownError

    msgs = [
        CrdtMessage(ts(1_700_000_000_000 + i), "todo", f"r\x00ow{i}", "title", f"v\x00al{i}")
        for i in range(5)
    ]
    dumps = []
    for backend in ("python", "native"):
        db = open_database(backend=backend)
        bootstrap(db)
        # No upserts planned (mask all False via planner contract):
        # messages land in __message only, full bytes preserved.
        if hasattr(db, "apply_planned"):
            with db.transaction():
                db.apply_planned(msgs, [False] * len(msgs))
        else:
            with db.transaction():
                db.run_many(
                    'INSERT INTO "__message" ("timestamp", "table", "row", "column", "value") '
                    "VALUES (?, ?, ?, ?, ?) ON CONFLICT DO NOTHING",
                    [(m.timestamp, m.table, m.row, m.column, m.value) for m in msgs],
                )
        dumps.append(db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'))
        db.close()
    assert dumps[0] == dumps[1]
    assert "r\x00ow0" in {r[2] for r in dumps[0]}  # NUL survived, not truncated

    # Upsert with a NUL identifier: Python's quote_ident raises; the C
    # path must refuse too (rc 3), not truncate into a different table.
    db = open_database(backend="native")
    bootstrap(db)
    bad = CrdtMessage(ts(1_700_000_000_001), "to\x00do", "r", "title", "x")
    with pytest.raises(UnknownError):
        with db.transaction():
            db.apply_planned([bad], [True])
    db.close()


def test_null_timestamp_row_does_not_crash_native_backend():
    """SQLite's legacy quirk lets a non-INTEGER BLOB PRIMARY KEY hold
    NULL; a tampered DB must yield defined behavior (NULL = no winner),
    not a null-pointer read, on both the fetch_winners and
    apply_sequential hot paths (ADVICE r1 low)."""
    db = open_database(backend="native")
    bootstrap(db)
    db.run(
        'INSERT INTO "__message" ("timestamp", "table", "row", "column", "value") '
        "VALUES (NULL, 'todo', 'r1', 'title', 'ghost')"
    )
    # fetch_winners: the NULL row is the only row for the cell. MAX/
    # ORDER BY DESC places NULL last, so it is also what the scan sees.
    winners = db.fetch_winners([("todo", "r1", "title")])
    assert winners == [None] or winners == [""] or winners[0] is None
    # apply_sequential: NULL winner treated as absent -> message wins.
    m = CrdtMessage(ts(1_700_000_000_000), "todo", "r1", "title", "real")
    mask = db.apply_sequential([m])
    assert list(mask) == [True]
    rows = db.exec('SELECT "title" FROM "todo" WHERE "id" = \'r1\'')
    assert rows == [("real",)]
    db.close()


def test_packed_query_reader_full_type_matrix():
    """`eh_exec_packed` + `unpack_packed_rows` (SURVEY hot loop #4)
    must reproduce the per-cell path exactly for every SQLite storage
    class — ints at 64-bit extremes, floats incl. inf/-0.0, unicode
    and NUL-bearing text, NUL-bearing blobs, nulls — and the raw bytes
    must be deterministic for an unchanged result set (they are the
    reactive loop's change detector)."""
    from evolu_tpu.storage.native import unpack_packed_rows

    cpp = CppSqliteDatabase()
    py = PySqliteDatabase()
    rows = [
        (1, "plain"), (2, None), (3, 2.5), (4, b"\x00\xff\x00"),
        (2**63 - 1, "max"), (-(2**63), "min"), (6, float("inf")),
        (7, -0.0), (8, "uni ✓ café"), (9, "nul\x00in\x00text"),
        (10, b""), (11, ""),
    ]
    for db in (cpp, py):
        db.exec('CREATE TABLE "t" ("a", "b")')
        db.run_many('INSERT INTO "t" VALUES (?, ?)', rows)
    sql = 'SELECT "a", "b" FROM "t" ORDER BY "a"'
    want = py.exec_sql_query(sql)
    got = cpp.exec_sql_query(sql)  # routes through the packed reader
    assert got == want
    raw1 = cpp.exec_sql_query_packed_raw(sql)
    raw2 = cpp.exec_sql_query_packed_raw(sql)
    assert raw1 == raw2
    assert unpack_packed_rows(raw1) == want
    # Empty result set: header only, parses to [].
    raw_empty = cpp.exec_sql_query_packed_raw('SELECT "a" FROM "t" WHERE "a" = -42')
    assert unpack_packed_rows(raw_empty) == []
    cpp.close(), py.close()


def test_unpack_changed_rows_matches_full_unpack():
    """The r5 row-granular unpack (`unpack_changed_rows`) must produce
    EXACTLY `unpack_packed_rows(raw)` for any pair of consecutive
    result sets — in-place edits (same and different encoded length),
    appends, deletions, reorders, type changes, NULL/blob values, and
    empty↔nonempty transitions — while reusing the previous result's
    dict OBJECTS for rows whose packed bytes are unchanged."""
    import random

    from evolu_tpu.storage.native import (
        native_available,
        open_database,
        unpack_changed_rows,
        unpack_packed_rows,
    )

    if not native_available():
        pytest.skip("native backend unavailable")
    db = open_database(backend="auto")
    db.exec('CREATE TABLE "t" ("id" TEXT PRIMARY KEY, "a" BLOB, "b" BLOB)')
    rng = random.Random(5)
    SQL = 'SELECT * FROM "t" ORDER BY "id"'

    def populate(n, mutate=None):
        db.run('DELETE FROM "t"', ())
        for i in range(n):
            v = (mutate or {}).get(i, f"val{i}")
            db.run('INSERT INTO "t" VALUES (?, ?, ?)',
                   (f"id{i:05d}", v, i * (1.5 if i % 3 else 1)))

    populate(300)
    prev_raw, prev_offs = db.exec_sql_query_packed_raw(SQL, (), with_offsets=True)
    prev_rows = unpack_packed_rows(prev_raw)

    # In-place same-length edit: exactly one fresh dict, rest reused.
    populate(300, {50: "VAL50"})
    raw, offs = db.exec_sql_query_packed_raw(SQL, (), with_offsets=True)
    got = unpack_changed_rows(raw, offs, prev_raw, prev_offs, prev_rows)
    assert got == unpack_packed_rows(raw)
    assert sum(g is p for g, p in zip(got, prev_rows)) == 299

    # Append keeps the whole previous prefix by identity.
    populate(310)
    raw, offs = db.exec_sql_query_packed_raw(SQL, (), with_offsets=True)
    got = unpack_changed_rows(raw, offs, prev_raw, prev_offs, prev_rows)
    assert got == unpack_packed_rows(raw)

    # Random mutation chains (incl. NULL/blob/length changes/shrink).
    for trial in range(40):
        n = rng.randrange(0, 40)
        mutate = {
            i: rng.choice([None, b"\x00\xffbin", "m" * rng.randrange(1, 9), 7, 2.5])
            for i in rng.sample(range(max(n, 1)), min(n, rng.randrange(0, 6)))
        }
        populate(n, mutate)
        raw, offs = db.exec_sql_query_packed_raw(SQL, (), with_offsets=True)
        got = unpack_changed_rows(raw, offs, prev_raw, prev_offs, prev_rows)
        assert got == unpack_packed_rows(raw), trial
        prev_raw, prev_offs, prev_rows = raw, offs, got
    db.close()
