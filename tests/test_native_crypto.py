"""Batched C++ OpenPGP layer (native/evolu_crypto.cpp) — exact-behavior
parity with the Python oracle (sync/crypto.py + protocol.py), fallback
demotion for every non-canonical shape, and live GnuPG interop in both
directions (reference: packages/evolu/src/sync.worker.ts:50-91,135-173
encrypts with OpenPGP.js v5; gpg is the independent RFC 4880 peer)."""

import pathlib
import shutil
import subprocess

import pytest

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.sync import native_crypto, protocol
from evolu_tpu.sync.client import decrypt_messages, encrypt_messages
from evolu_tpu.sync.crypto import PgpError, decrypt_symmetric, encrypt_symmetric

FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures"
MN = (FIXTURES / "gpg_password.txt").read_text().strip()

pytestmark = pytest.mark.skipif(
    not native_crypto.native_available(), reason="libevolu_crypto unavailable"
)

# Value matrix: every CrdtValue kind, both int fields (5/int32, 7/int64),
# unicode, NULs (the char*-ABI trap), empty strings, float edge cases.
VALUES = [
    None, "", "x", "héllo ✓ café", "with\x00nul\x00s", "日本語",
    True, False, 0, 1, -1, 2**31 - 1, -(2**31), 2**31, -(2**31) - 1,
    2**63 - 1, -(2**63), 3.14159, -0.0, 1e308, float("inf"), float("-inf"),
]


def _msgs(values=VALUES):
    return tuple(
        CrdtMessage(f"ts{i}", "todo\x00tbl", f"row-{i}", "col\x00umn", v)
        for i, v in enumerate(values)
    )


def _canon(m):
    # bools leave encode_content as varints; both paths decode them as ints
    v = int(m.value) if isinstance(m.value, bool) else m.value
    return CrdtMessage(m.timestamp, m.table, m.row, m.column, v)


def test_native_encrypt_decrypts_via_pure_oracle():
    msgs = _msgs()
    enc = native_crypto.encrypt_batch(msgs, MN)
    assert enc is not None and len(enc) == len(msgs)
    for m, e in zip(msgs, enc):
        assert e.timestamp == m.timestamp
        content = decrypt_symmetric(e.content, MN)
        assert protocol.decode_content(content) == (
            m.table, m.row, m.column,
            int(m.value) if isinstance(m.value, bool) else m.value,
        )
        # and the content bytes are exactly what the Python encoder emits
        assert content == protocol.encode_content(m.table, m.row, m.column, m.value)


def test_pure_encrypt_decrypts_via_native_batch():
    msgs = _msgs()
    enc = tuple(
        protocol.EncryptedCrdtMessage(
            m.timestamp,
            encrypt_symmetric(
                protocol.encode_content(m.table, m.row, m.column, m.value), MN
            ),
        )
        for m in msgs
    )
    assert native_crypto.decrypt_batch(enc, MN) == tuple(_canon(m) for m in msgs)


def test_pipeline_roundtrip_via_public_entry_points():
    msgs = _msgs()
    assert decrypt_messages(encrypt_messages(msgs, MN), MN) == tuple(
        _canon(m) for m in msgs
    )


def test_unencodable_values_fall_back_to_oracle_errors():
    # bytes can never travel the wire; int beyond int64 exceeds the codec
    for bad in (b"raw", 2**64):
        msgs = (CrdtMessage("t", "todo", "r", "c", bad),)
        assert native_crypto.encrypt_batch(msgs, MN) is None
        with pytest.raises(TypeError):
            encrypt_messages(msgs, MN)


def test_nondeterministic_and_distinct_salts():
    msgs = _msgs(["same"] * 3)
    enc = native_crypto.encrypt_batch(msgs, MN)
    cts = [e.content for e in enc]
    assert len(set(cts)) == 3  # fresh salt + prefix per message
    salts = {ct[6:14] for ct in cts}  # SKESK v4 salt offset
    assert len(salts) == 3


def test_wrong_password_raises_identically():
    enc = native_crypto.encrypt_batch(_msgs(["v"]), MN)
    with pytest.raises(PgpError, match="wrong password"):
        native_crypto.decrypt_batch(enc, "not the password")
    with pytest.raises(PgpError, match="wrong password"):
        decrypt_messages(enc, "not the password")


def test_mdc_tamper_detected_through_batch():
    enc = native_crypto.encrypt_batch(_msgs(["v"]), MN)
    ct = bytearray(enc[0].content)
    ct[-1] ^= 0x01  # inside the MDC trailer
    bad = (protocol.EncryptedCrdtMessage("t", bytes(ct)),)
    with pytest.raises(PgpError):
        native_crypto.decrypt_batch(bad, MN)


def test_malformed_first_failure_order_matches_pure():
    """Mixed batch: [good, malformed, good] must raise the malformed
    message's error (not return partial results), like the pure loop."""
    good = native_crypto.encrypt_batch(_msgs(["a", "b"]), MN)
    batch = (good[0], protocol.EncryptedCrdtMessage("t", b"\x00garbage"), good[1])
    with pytest.raises(PgpError):
        native_crypto.decrypt_batch(batch, MN)


def test_gpg_golden_ciphertexts_via_batch():
    """The frozen gpg fixtures: 'none' decodes on the canonical fast
    path; zip/zlib are Compressed Data → demoted to the oracle, same
    result either way."""
    plaintext = (FIXTURES / "gpg_plaintext.bin").read_bytes()
    expected = protocol.decode_content(plaintext)
    for name in (
        "gpg_aes256_s2k1024_none.pgp",
        "gpg_aes256_s2k1024_zip.pgp",
        "gpg_aes256_s2k1024_zlib.pgp",
    ):
        enc = (protocol.EncryptedCrdtMessage("t", (FIXTURES / name).read_bytes()),)
        (out,) = native_crypto.decrypt_batch(enc, MN)
        assert (out.table, out.row, out.column, out.value) == expected, name


@pytest.mark.skipif(shutil.which("gpg") is None, reason="gpg not on PATH")
def test_gpg_decrypts_native_ciphertext(tmp_path):
    """Live interop: a ciphertext the C++ path produced must decrypt
    with GnuPG to the exact content bytes."""
    msgs = (CrdtMessage("t", "todo", "r-1", "title", "Buy milk ✓ café"),)
    enc = native_crypto.encrypt_batch(msgs, MN)
    ct_file = tmp_path / "msg.pgp"
    ct_file.write_bytes(enc[0].content)
    res = subprocess.run(
        [
            "gpg", "--homedir", str(tmp_path), "--batch",
            "--pinentry-mode", "loopback", "--passphrase", MN,
            "--decrypt", str(ct_file),
        ],
        capture_output=True,
        check=True,
    )
    assert res.stdout == protocol.encode_content("todo", "r-1", "title", "Buy milk ✓ café")


@pytest.mark.skipif(shutil.which("gpg") is None, reason="gpg not on PATH")
def test_native_decrypts_fresh_gpg_ciphertext(tmp_path):
    """Live interop the other way: encrypt with gpg NOW (fresh salt,
    its own packet writer) and decrypt through the batch."""
    content = protocol.encode_content("todo", "r-2", "done", 1)
    src = tmp_path / "plain.bin"
    src.write_bytes(content)
    out = tmp_path / "out.pgp"
    subprocess.run(
        [
            "gpg", "--homedir", str(tmp_path), "--batch", "--yes",
            "--pinentry-mode", "loopback", "--passphrase", MN,
            "--symmetric", "--cipher-algo", "AES256",
            "--s2k-mode", "3", "--s2k-digest-algo", "SHA256",
            "--s2k-count", "1024", "--compress-algo", "none",
            "--output", str(out), str(src),
        ],
        capture_output=True,
        check=True,
    )
    enc = (protocol.EncryptedCrdtMessage("t", out.read_bytes()),)
    (msg,) = native_crypto.decrypt_batch(enc, MN)
    assert (msg.table, msg.row, msg.column, msg.value) == ("todo", "r-2", "done", 1)


def _oracle_vs_native(content: bytes):
    """Encrypt crafted content bytes with the pure path, then compare
    the native batch outcome against the oracle outcome-for-outcome."""
    ct = encrypt_symmetric(content, MN)
    enc = (protocol.EncryptedCrdtMessage("t", ct),)
    try:
        oracle = protocol.decode_content(decrypt_symmetric(ct, MN))
    except (PgpError, ValueError) as e:
        oracle = type(e)
    try:
        (m,) = native_crypto.decrypt_batch(enc, MN)
        got = (m.table, m.row, m.column, m.value)
    except (PgpError, ValueError) as e:
        got = type(e)
    assert got == oracle, f"{content!r}: oracle {oracle!r} vs native {got!r}"


def test_ten_byte_varint_overflow_matches_oracle():
    """The Python varint reader keeps UNBOUNDED precision on the 10th
    byte; a mod-2^64 wrap in C++ would remap overflowed field keys to
    real fields, decode overflowed lengths 'successfully', and bend
    field-7 ints (r4 review finding). All such shapes must demote to
    the oracle."""
    base = protocol.encode_content("todo", "r", "c", None)
    ten = lambda last: bytes([0x80] * 9 + [last])  # 9 continuations + final
    crafted = [
        # field 7 varint whose 10th byte carries bits >= 2^64: the
        # oracle decodes a huge positive Python int
        base + bytes([7 << 3]) + ten(0x05),
        # overflowed FIELD KEY (2^64 + tag(1, wt2) = 0x8A 0x80×8 0x02):
        # a huge unknown field to the oracle (payload skipped), would
        # wrap to field 1 = table in C++
        base + bytes([0x8A] + [0x80] * 8 + [0x02]) + bytes([3]) + b"zzz",
        # overflowed wt2 LENGTH (2^64 + 3): oracle raises truncated
        bytes([(1 << 3) | 2]) + ten(0x03) + b"abc" + base,
        # the maximal legitimate 10-byte varint (bit 63 set, 10th byte
        # 0x01): both paths must decode int64 min
        base + bytes([7 << 3]) + bytes([0x80] * 9 + [0x01]),
        # 10th byte with continuation set: oracle raises varint too long
        base + bytes([7 << 3]) + bytes([0x80] * 10 + [0x00]),
    ]
    for content in crafted:
        _oracle_vs_native(content)


def test_fused_push_request_matches_pure_encoder():
    """`encode_push_request` must be structurally byte-compatible with
    `protocol.encode_sync_request`: same field order, a decodable
    messages stream whose ciphertexts the pure oracle decrypts to the
    exact contents, and identical trailing scalar fields."""
    msgs = _msgs()
    body = native_crypto.encode_push_request(msgs, MN, "user-1", "f" * 16, '{"h":1}')
    assert body is not None
    req = protocol.decode_sync_request(body)
    assert (req.user_id, req.node_id, req.merkle_tree) == ("user-1", "f" * 16, '{"h":1}')
    assert len(req.messages) == len(msgs)
    for m, e in zip(msgs, req.messages):
        assert e.timestamp == m.timestamp
        assert protocol.decode_content(decrypt_symmetric(e.content, MN)) == (
            m.table, m.row, m.column,
            int(m.value) if isinstance(m.value, bool) else m.value,
        )
    tail = protocol.encode_sync_request(
        protocol.SyncRequest((), "user-1", "f" * 16, '{"h":1}')
    )
    assert body.endswith(tail)
    # Unencodable values route the WHOLE batch to the pure path.
    assert native_crypto.encode_push_request(
        (CrdtMessage("t", "todo", "r", "c", b"raw"),), MN, "u", "n", "{}"
    ) is None


def test_fused_response_decode_parity_and_fallbacks():
    """`decrypt_response` == decode_sync_response + decrypt_messages
    for canonical rows, demotes non-canonical ciphertexts per message
    (a gpg ZIP-compressed fixture decrypts identically through the
    oracle at its position), falls back wholesale on non-canonical
    wire, and raises the oracle's errors."""
    msgs = _msgs()
    enc = list(native_crypto.encrypt_batch(msgs, MN))
    # Splice in a compressed gpg ciphertext (canonical-path reject).
    gpg_ct = (FIXTURES / "gpg_aes256_s2k1024_zip.pgp").read_bytes()
    enc.insert(3, protocol.EncryptedCrdtMessage("ts-gpg", gpg_ct))
    resp_bytes = protocol.encode_sync_response(
        protocol.SyncResponse(tuple(enc), '{"t":2}')
    )
    fused = native_crypto.decrypt_response(resp_bytes, MN)
    assert fused is not None
    got_msgs, got_tree = fused
    resp = protocol.decode_sync_response(resp_bytes)
    from evolu_tpu.sync.client import decrypt_messages

    assert got_msgs == decrypt_messages(resp.messages, MN)
    assert got_tree == '{"t":2}'

    with pytest.raises(PgpError, match="wrong password"):
        native_crypto.decrypt_response(resp_bytes, "nope")
    # Garbage / non-canonical wire: wholesale fallback (None), so the
    # pure decoder owns the ValueError surface.
    assert native_crypto.decrypt_response(b"\x07garbage", MN) is None
    # Truncated by one byte (the tree field's length no longer fits):
    # also wholesale fallback, mirroring the pure decoder's ValueError.
    assert native_crypto.decrypt_response(resp_bytes[:-1], MN) is None
    with pytest.raises(ValueError):
        protocol.decode_sync_response(resp_bytes[:-1])


def test_overflow_length_varints_cannot_escape_bounds():
    """r4 review finding: a 10-byte length varint carrying bit 63 would
    wrap a naive `pos + len > n` check and drive heap over-reads on
    untrusted response bytes (the bit-flip fuzz can't synthesize this
    shape). All such inputs must demote cleanly — fused → None /
    oracle error, never a crash — matching the pure decoder's
    ValueError."""
    huge = bytes([0xFF] * 9 + [0x01])  # varint = 2^64 - 1
    crafted = [
        # SyncResponse: field 1 with a wrapping length, then filler.
        bytes([0x0A]) + huge + b"\x0a\x03abc" * 4,
        # field 2 (merkleTree) with a wrapping length.
        bytes([0x12]) + huge + b"xx",
        # nested: valid message wrapper whose INNER field length wraps.
        bytes([0x0A, 0x0C, 0x0A]) + huge + b"\x00",
    ]
    for data in crafted:
        assert native_crypto.decrypt_response(data, MN) is None, data.hex()
        with pytest.raises(ValueError):
            protocol.decode_sync_response(data)
    # The same shape inside a decrypted CONTENT (decode_content's wt2):
    # oracle raises; the canonical path must demote, not over-read.
    content = protocol.encode_content("t", "r", "c", None) + bytes([0x22]) + huge
    _oracle_vs_native(content)


def test_fuzz_decrypt_response_never_diverges_from_oracle():
    """Random mutations of response bytes: whenever the fused C walker
    accepts the wire (returns non-None), its outcome must equal the
    pure decode+decrypt outcome exactly — value or error type. (A None
    means production runs the pure path, equal by definition.)"""
    import random

    from evolu_tpu.sync.client import decrypt_messages

    rng = random.Random(13)
    base_msgs = _msgs(["a", 7, None])
    enc = native_crypto.encrypt_batch(base_msgs, MN)
    base = protocol.encode_sync_response(protocol.SyncResponse(enc, '{"x":1}'))
    for trial in range(150):
        b = bytearray(base)
        for _ in range(rng.randint(1, 5)):
            op = rng.random()
            if op < 0.6 and b:
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            elif op < 0.8 and len(b) > 2:
                del b[rng.randrange(len(b))]
            else:
                b.insert(rng.randrange(len(b) + 1), rng.randrange(256))
        data = bytes(b)
        try:
            fused = native_crypto.decrypt_response(data, MN)
        except (PgpError, ValueError) as e:
            fused = type(e)
        if fused is None:
            continue  # production falls back to the pure path
        try:
            resp = protocol.decode_sync_response(data)
            oracle = (decrypt_messages(resp.messages, MN), resp.merkle_tree)
        except (PgpError, ValueError) as e:
            oracle = type(e)
        assert fused == oracle, f"trial {trial}"


def test_fuzz_decrypt_batch_never_diverges_from_oracle():
    """Random mutations of valid ciphertexts: the batch path must
    either produce the oracle's value or raise the oracle's error —
    never a third outcome."""
    import random

    rng = random.Random(7)
    base = native_crypto.encrypt_batch(_msgs(["fuzz-me", 42, None]), MN)
    for trial in range(120):
        ct = bytearray(rng.choice(base).content)
        for _ in range(rng.randint(1, 4)):
            op = rng.random()
            if op < 0.5 and ct:
                ct[rng.randrange(len(ct))] ^= 1 << rng.randrange(8)
            elif op < 0.75 and len(ct) > 2:
                del ct[rng.randrange(len(ct))]
            else:
                ct.insert(rng.randrange(len(ct) + 1), rng.randrange(256))
        enc = (protocol.EncryptedCrdtMessage("t", bytes(ct)),)
        try:
            oracle = protocol.decode_content(decrypt_symmetric(bytes(ct), MN))
        except (PgpError, ValueError) as e:
            oracle = type(e)
        try:
            (m,) = native_crypto.decrypt_batch(enc, MN)
            got = (m.table, m.row, m.column, m.value)
        except (PgpError, ValueError) as e:
            got = type(e)
        assert got == oracle, f"trial {trial}: oracle {oracle!r} vs got {got!r}"
