"""Observability subsystem (evolu_tpu/obs) — registry semantics, the
relay's /metrics + /stats endpoints against driven traffic (single
process and MultiprocessRelay), winner-cache hit/miss counters under a
scripted access pattern, host-fallback counter exactness on a
non-canonical batch, sync wire counters, the flight recorder riding
worker-boundary exceptions, and Logger integration (span histograms,
duration_summary, one-call clear)."""

import json
import re
import urllib.request

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.obs import flight, metrics
from evolu_tpu.server.relay import (
    MultiprocessRelay,
    RelayServer,
    RelayStore,
    ShardedRelayStore,
)
from evolu_tpu.sync import protocol
from evolu_tpu.utils.log import logger

BASE = 1_700_000_000_000


@pytest.fixture(autouse=True)
def _clean_slate():
    logger.clear()  # resets ring + durations + metrics registry + flight
    yield
    logger.configure(False)
    logger.clear()


# --- registry semantics ---


def test_counter_gauge_histogram_roundtrip():
    metrics.inc("t_total", 3, kind="a")
    metrics.inc("t_total", kind="a")
    metrics.inc("t_total", kind="b")
    assert metrics.get_counter("t_total", kind="a") == 4
    assert metrics.get_counter("t_total", kind="b") == 1
    assert metrics.get_counter("t_total", kind="missing") == 0
    metrics.set_gauge("t_gauge", 7.5)
    assert metrics.registry.get_gauge("t_gauge") == 7.5
    for v in (0.1, 1.0, 100.0):
        metrics.observe("t_ms", v)
    edges, cum, total, count = metrics.registry.get_histogram("t_ms")
    assert count == 3 and total == pytest.approx(101.1)
    assert cum[-1] == 3  # +Inf cumulative = count
    assert all(b <= a for b, a in zip(cum, cum[1:]))  # monotone


def test_histogram_quantile_estimates_within_buckets():
    for _ in range(100):
        metrics.observe("q_ms", 1.0)
    q = metrics.quantile("q_ms", 0.5)
    # 1.0 lands in the (0.5, 1.0] bucket of the x2 latency family.
    assert 0.5 <= q <= 1.0


def test_reset_keeps_bucket_shape():
    metrics.observe("r_ms", 5.0, buckets=(1.0, 10.0))
    metrics.reset()
    metrics.observe("r_ms", 5.0)
    edges, _, _, count = metrics.registry.get_histogram("r_ms")
    assert edges == (1.0, 10.0) and count == 1


def test_quantile_clamps_overflow_to_top_finite_edge():
    """ISSUE 15 registry hardening: mass in the overflow bucket — via
    the implicit +Inf bucket OR an explicitly registered inf edge —
    must estimate to the TOP FINITE bucket edge, never inf (dashboards
    need a plottable number)."""
    import math

    reg = metrics.MetricsRegistry()
    reg.observe("imp_ms", 1e9)  # far past the latency family's top edge
    q = reg.quantile("imp_ms", 0.99)
    assert q is not None and math.isfinite(q)
    assert q == metrics.LATENCY_MS_BUCKETS[-1]
    reg.observe("exp_ms", 50.0, buckets=(1.0, 10.0, float("inf")))
    reg.observe("exp_ms", 60.0)
    q = reg.quantile("exp_ms", 0.5)
    assert q == 10.0  # top FINITE edge, though mass sits in the inf bucket
    # Interpolation inside finite buckets is unchanged.
    reg.observe("mid_ms", 5.0, buckets=(1.0, 10.0, float("inf")))
    assert 1.0 <= reg.quantile("mid_ms", 0.5) <= 10.0


def test_snapshot_reset_hammer_loses_no_events():
    """ISSUE 15 registry hardening: `snapshot(reset=True)` drains
    atomically — with writer threads hammering inc()/observe(), the sum
    across drained windows plus the final residue must equal exactly
    what the writers recorded. A separate snapshot();reset() pair loses
    whatever lands between the two calls; this pins the one-lock
    contract."""
    import threading

    reg = metrics.MetricsRegistry()
    N_THREADS, N_EVENTS = 4, 2000
    stop = threading.Event()

    def writer():
        for _ in range(N_EVENTS):
            reg.inc("h_total")
            reg.observe("h_ms", 1.0, buckets=(10.0,))

    threads = [threading.Thread(target=writer) for _ in range(N_THREADS)]
    drained_counter = 0.0
    drained_hist = 0
    for t in threads:
        t.start()
    try:
        while any(t.is_alive() for t in threads):
            snap = reg.snapshot(reset=True)
            for s in snap["counters"].get("h_total", []):
                drained_counter += s["value"]
            for s in snap["histograms"].get("h_ms", []):
                drained_hist += s["count"]
    finally:
        stop.set()
        for t in threads:
            t.join()
    final = reg.snapshot(reset=True)
    for s in final["counters"].get("h_total", []):
        drained_counter += s["value"]
    for s in final["histograms"].get("h_ms", []):
        drained_hist += s["count"]
    assert drained_counter == N_THREADS * N_EVENTS
    assert drained_hist == N_THREADS * N_EVENTS


def test_build_info_and_process_gauges(tmp_path):
    """ISSUE 15 satellite: `evolu_build_info` (facts in labels) +
    uptime/RSS process gauges surface on /metrics so a fleet dashboard
    can tell relay topologies apart without SSH."""
    server = RelayServer(RelayStore()).start()
    try:
        text = _get(server.url + "/metrics")
        m = re.search(r"^evolu_build_info\{([^}]*)\} 1$", text, re.M)
        assert m, "evolu_build_info gauge missing from /metrics"
        labels = dict(
            kv.split("=", 1) for kv in re.findall(r'[a-z_]+="[^"]*"', m.group(1))
        )
        assert labels['version'].strip('"')
        assert labels['backend'].strip('"') in ("native", "python")
        assert labels['write_behind'].strip('"') == "0"
        assert labels['connection_tier'].strip('"') in ("threaded", "eventloop")
        assert "mesh_engine" in labels and "push" in labels
        parsed = _parse_prometheus(text)
        up = parsed[("evolu_process_uptime_seconds", frozenset())]
        assert up >= 0
        rss = parsed.get(("evolu_process_rss_bytes", frozenset()))
        assert rss is None or rss > 1 << 20  # >1MB if the probe worked
    finally:
        server.stop()


def test_prometheus_exposition_is_valid_and_escaped():
    metrics.inc("e_total", 2, path='we"ird\\x', note="a\nb")
    metrics.observe("e_ms", 3.0)
    text = metrics.render_prometheus()
    line_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+;inf]+$'
    )
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ", line)
        else:
            assert line_re.match(line), line
    assert 'path="we\\"ird\\\\x"' in text
    assert 'note="a\\nb"' in text
    assert "e_ms_bucket" in text and 'le="+Inf"' in text
    assert "e_ms_sum 3" in text and "e_ms_count 1" in text
    # snapshot carries the same data, JSON-serializably
    snap = json.loads(metrics.registry.snapshot_json())
    assert snap["counters"]["e_total"][0]["value"] == 2


def test_label_cardinality_bound_folds_into_overflow():
    """ISSUE 10: data-driven label values (per-owner freshness gauges)
    must never grow the registry unboundedly — past the per-family
    cap, NEW label sets fold into "__overflow__" and are counted."""
    reg = metrics.MetricsRegistry()
    reg.label_cardinality_cap = 4
    for i in range(10):
        reg.set_gauge("t_fresh", i, owner=f"o{i}", peer="p")
    # 4 admitted + the one folded overflow series.
    assert len(reg._gauges["t_fresh"]) == 5
    assert reg.get_gauge("t_fresh", owner="o3", peer="p") == 3
    assert reg.get_gauge(
        "t_fresh", owner="__overflow__", peer="__overflow__") == 9  # last write
    assert reg.get_counter("evolu_obs_label_overflow_total",
                           family="t_fresh") == 6
    # Existing series keep updating in place — no new fold.
    reg.set_gauge("t_fresh", 33, owner="o3", peer="p")
    assert reg.get_gauge("t_fresh", owner="o3", peer="p") == 33
    assert reg.get_counter("evolu_obs_label_overflow_total",
                           family="t_fresh") == 6
    # Counters and histograms share the bound.
    for i in range(10):
        reg.inc("t_total", owner=f"o{i}")
        reg.observe("t_ms", 1.0, owner=f"o{i}")
    assert len(reg._counters["t_total"]) == 5
    assert len(reg._hists["t_ms"]) == 5
    assert reg.get_counter("t_total", owner="__overflow__") == 6
    # Unlabeled series never fold (one series can't explode).
    for _ in range(10):
        reg.inc("t_plain_total")
    assert reg.get_counter("t_plain_total") == 10
    # Exposition stays valid with the folded series present.
    assert 'owner="__overflow__"' in reg.render_prometheus()


def test_histogram_exemplars_latest_wins_and_render_opt_in():
    metrics.observe("ex_ms", 5.0, exemplar="a" * 32)
    metrics.observe("ex_ms", 7.0, exemplar="b" * 32)
    metrics.observe("ex_ms", 9.0)  # exemplar-less observe keeps the last
    tid, value, ts = metrics.registry.get_exemplar("ex_ms")
    assert tid == "b" * 32 and value == 7.0 and ts > 0
    snap = metrics.snapshot()
    (series,) = snap["histograms"]["ex_ms"]
    assert series["exemplar"][0] == "b" * 32
    # Default text exposition is plain 0.0.4; exemplars are opt-in.
    assert "trace_id" not in metrics.render_prometheus()
    assert '# {trace_id="' + "b" * 32 + '"}' in \
        metrics.registry.render_prometheus(exemplars=True)


def test_disabled_registry_records_nothing():
    metrics.set_enabled(False)
    try:
        metrics.inc("d_total")
        metrics.observe("d_ms", 1.0)
    finally:
        metrics.set_enabled(True)
    assert metrics.get_counter("d_total") == 0
    assert metrics.registry.get_histogram("d_ms") is None


# --- Logger integration ---


def test_span_feeds_histogram_and_duration_summary():
    for _ in range(4):
        with logger.span("kernel:merge", "unit"):
            pass
    summary = logger.duration_summary("kernel:merge")
    assert summary["count"] == 4
    assert summary["mean_ms"] == pytest.approx(summary["total_ms"] / 4)
    assert summary["max_ms"] >= summary["mean_ms"]
    assert "p50_ms" in summary and summary["p50_ms"] > 0
    _, _, _, count = metrics.registry.get_histogram(
        "evolu_kernel_span_ms", target="kernel:merge"
    )
    assert count == 4


def test_logger_clear_resets_registry_and_flight():
    metrics.inc("c_total")
    flight.record("dev", "before clear")
    logger.clear()
    assert metrics.get_counter("c_total") == 0
    assert flight.dump() == []


def test_flight_records_disabled_log_targets():
    """The recorder exists for events nobody was watching: a log() on a
    console-disabled target must still land in the flight ring."""
    logger.configure(False)
    logger.log("sync:request", "invisible", n=1)
    assert logger.recent_events() == []  # console ring stays gated
    evs = flight.dump()
    assert any(e.target == "sync:request" and e.message == "invisible" for e in evs)


def test_flight_attach_is_idempotent_and_noted():
    flight.record("dev", "breadcrumb", step=1)
    e = ValueError("boom")
    flight.attach(e)
    first = e.flight_records
    assert any(ev.message == "breadcrumb" for ev in first)
    flight.record("dev", "later")
    flight.attach(e)  # nested boundary: keeps the innermost dump
    assert e.flight_records is first


# --- winner-cache counters (scripted access pattern) ---


def _cache_db():
    from evolu_tpu.storage.native import open_database
    from evolu_tpu.storage.schema import init_db_model

    db = open_database(":memory:", "auto")
    init_db_model(db, mnemonic=None)
    db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title" BLOB)')
    return db


def _msg(i, row):
    return CrdtMessage(
        timestamp_to_string(Timestamp(BASE + i * 1000, 0, "a1b2c3d4e5f60718")),
        "todo", row, "title", f"v{i}",
    )


def test_winner_cache_hit_miss_counters_match_scripted_pattern():
    from evolu_tpu.ops.winner_cache import DeviceWinnerCache
    from evolu_tpu.storage.apply import apply_messages

    db = _cache_db()
    cache = DeviceWinnerCache(db, adaptive=False)  # pin the cached path
    tree = {}
    try:
        # Batch 1: 5 fresh cells -> 5 misses, 0 hits, 5 seeds.
        batch1 = [_msg(i, f"r{i}") for i in range(5)]
        tree = apply_messages(db, tree, batch1, planner=cache.plan_batch)
        assert metrics.get_counter("evolu_winner_cache_misses_total") == 5
        assert metrics.get_counter("evolu_winner_cache_hits_total") == 0
        assert metrics.get_counter("evolu_winner_cache_seeded_cells_total") == 5
        # Batch 2: the same 5 cells -> 5 hits, no new misses or seeds.
        batch2 = [_msg(10 + i, f"r{i}") for i in range(5)]
        tree = apply_messages(db, tree, batch2, planner=cache.plan_batch)
        assert metrics.get_counter("evolu_winner_cache_hits_total") == 5
        assert metrics.get_counter("evolu_winner_cache_misses_total") == 5
        assert metrics.get_counter("evolu_winner_cache_seeded_cells_total") == 5
        # Batch 3: 3 known + 2 fresh -> hits 5+3, misses 5+2.
        batch3 = [_msg(20 + i, f"r{i}") for i in range(3)] + [
            _msg(30 + i, f"new{i}") for i in range(2)
        ]
        tree = apply_messages(db, tree, batch3, planner=cache.plan_batch)
        assert metrics.get_counter("evolu_winner_cache_hits_total") == 8
        assert metrics.get_counter("evolu_winner_cache_misses_total") == 7
        # Invalidation accounting.
        cache.invalidate([("todo", "r0", "title"), ("todo", "absent", "title")])
        assert metrics.get_counter("evolu_winner_cache_invalidated_cells_total") == 1
    finally:
        db.close()


def test_host_fallback_counter_increments_exactly_on_noncanonical_batch():
    from evolu_tpu.ops.merge import plan_batch_device

    canonical = [_msg(0, "r0"), _msg(1, "r1")]
    plan_batch_device(canonical, {})
    assert metrics.get_counter("evolu_merge_host_fallbacks_total") == 0
    bad = [
        CrdtMessage("2023-09-01T10:00:00.000Z-0000-ABCDEF0123456789",
                    "todo", "rw", "title", "U"),
        _msg(2, "r2"),
    ]
    plan_batch_device(bad, {})
    assert metrics.get_counter("evolu_merge_host_fallbacks_total") == 1
    assert metrics.get_counter("evolu_merge_host_fallback_messages_total") == 2
    plan_batch_device(canonical, {})
    assert metrics.get_counter("evolu_merge_host_fallbacks_total") == 1


# --- sync transport wire counters ---


def test_sync_transport_counts_requests_and_bytes():
    from evolu_tpu.core.types import Owner
    from evolu_tpu.runtime.messages import SyncRequestInput
    from evolu_tpu.sync.client import SyncTransport
    from evolu_tpu.utils.config import Config

    ts = timestamp_to_string(Timestamp(BASE, 0, "89e3b4f11a2c5d70"))
    response = protocol.encode_sync_response(protocol.SyncResponse((), "{}"))
    posted = []

    def fake_post(url, body):
        posted.append(len(body))
        return response

    t = SyncTransport(Config(), on_receive=lambda *a: None, http_post=fake_post)
    try:
        t.request_sync(SyncRequestInput((), ts, "{}", Owner("o", "m")))
        t.flush()
    finally:
        t.stop()
    assert metrics.get_counter("evolu_sync_requests_total") == 1
    assert metrics.get_counter("evolu_sync_responses_total") == 1
    _, _, byte_sum, count = metrics.registry.get_histogram("evolu_sync_request_bytes")
    assert count == 1 and byte_sum == posted[0]
    _, _, resp_sum, _ = metrics.registry.get_histogram("evolu_sync_response_bytes")
    assert resp_sum == len(response)


# --- worker boundary: flight dump rides OnError ---


def test_worker_error_carries_flight_records():
    from evolu_tpu.runtime.client import create_evolu

    evolu = create_evolu({"todo": ("title",)})
    try:
        errors = []
        evolu.subscribe_error(errors.append)
        evolu.create("todo", {"title": "x"})  # leaves clock events in the ring
        evolu.worker.flush()
        evolu.worker.post(object())  # unknown command -> OnError(ValueError)
        evolu.worker.flush()
        assert errors, "unknown command must surface OnError"
        err = errors[0].error if hasattr(errors[0], "error") else errors[0]
        records = getattr(err, "flight_records", None)
        assert isinstance(records, list) and records, (
            "worker-boundary exceptions must carry the flight dump"
        )
        assert metrics.get_counter("evolu_worker_errors_total", command="object") == 1
    finally:
        evolu.dispose()


# --- relay endpoints ---


def _post(url, req):
    body = protocol.encode_sync_request(req)
    r = urllib.request.urlopen(
        urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/octet-stream"}
        ),
        timeout=30,
    )
    return protocol.decode_sync_response(r.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.read().decode("utf-8")


def _sync_req(user, node, n_msgs, start=0):
    msgs = tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (start + i) * 1000, 0, node)),
            b"ct-%d" % (start + i),
        )
        for i in range(n_msgs)
    )
    return protocol.SyncRequest(msgs, user, node, "{}")


def _parse_prometheus(text):
    """name{labels} value -> {(name, frozenset(label items)): float}."""
    out = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (.+)$", line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = frozenset(
            tuple(kv.split("=", 1)) for kv in re.findall(r'[^,{]+="[^"]*"', m.group(3) or "")
        )
        out[(m.group(1), labels)] = float(m.group(4).replace("+Inf", "inf"))
    return out


def _counter_sum(parsed, name):
    return sum(v for (n, _), v in parsed.items() if n == name)


def test_relay_metrics_and_stats_agree_with_driven_traffic():
    store = ShardedRelayStore(":memory:", shards=4)
    server = RelayServer(store).start()
    try:
        users = [f"user-{i}" for i in range(6)]
        for i, u in enumerate(users):
            _post(server.url, _sync_req(u, f"{i:016x}", n_msgs=3, start=i * 10))
        _post(server.url, _sync_req(users[0], "0" * 16, n_msgs=0))  # pull round

        parsed = _parse_prometheus(_get(server.url + "/metrics"))
        key = ("evolu_relay_requests_total", frozenset({("endpoint", '"/"')}))
        assert parsed[key] == 7
        # latency histogram: one observation per sync POST
        assert _counter_sum(
            {k: v for k, v in parsed.items() if k[0] == "evolu_relay_request_ms_count"},
            "evolu_relay_request_ms_count",
        ) == 7
        # per-shard counters cover every request exactly once
        shard_counts = {
            k[1]: v for k, v in parsed.items()
            if k[0] == "evolu_relay_shard_requests_total"
        }
        assert sum(shard_counts.values()) == 7
        expected_shards = {store.shard_index(u) for u in users} | {
            store.shard_index(users[0])
        }
        assert {
            int(dict(k)["shard"].strip('"')) for k in shard_counts
        } == expected_shards

        stats = json.loads(_get(server.url + "/stats"))
        assert stats["messages"] == 6 * 3  # every pushed row landed
        assert stats["users"] == 6
        assert stats["requests_total"] == 7
        assert len(stats["shards"]) == 4
        assert sum(s["messages"] for s in stats["shards"]) == 18
        assert sum(s["requests"] for s in stats["shards"]) == 7
        assert stats["latency_ms"]["count"] == 7
        # /metrics and /stats must agree with each other too
        assert _counter_sum(parsed, "evolu_relay_shard_requests_total") == (
            stats["requests_total"]
        )
    finally:
        server.stop()


def test_relay_metrics_include_client_side_counters_in_process():
    """The registry is process-global: a relay serving /metrics in the
    same process as kernel work exposes winner-cache hit/miss and
    host-fallback counts alongside its own — one scrape shows the whole
    pipeline's decisions, all driven by REAL traffic here (cache plans
    + a non-canonical batch + relay sync posts)."""
    from evolu_tpu.ops.merge import plan_batch_device
    from evolu_tpu.ops.winner_cache import DeviceWinnerCache
    from evolu_tpu.storage.apply import apply_messages

    db = _cache_db()
    cache = DeviceWinnerCache(db, adaptive=False)
    tree = apply_messages(
        db, {}, [_msg(i, f"r{i}") for i in range(4)], planner=cache.plan_batch
    )
    apply_messages(
        db, tree, [_msg(10 + i, f"r{i}") for i in range(4)],
        planner=cache.plan_batch,
    )
    plan_batch_device(
        [CrdtMessage("2023-09-01T10:00:00.000Z-0000-ABCDEF0123456789",
                     "todo", "rw", "title", "U")], {},
    )
    server = RelayServer(RelayStore()).start()
    try:
        _post(server.url, _sync_req("u1", "a" * 16, n_msgs=2))
        parsed = _parse_prometheus(_get(server.url + "/metrics"))
        assert parsed[("evolu_winner_cache_hits_total", frozenset())] == 4
        assert parsed[("evolu_winner_cache_misses_total", frozenset())] == 4
        assert parsed[("evolu_merge_host_fallbacks_total", frozenset())] == 1
        key = ("evolu_relay_requests_total", frozenset({("endpoint", '"/"')}))
        assert parsed[key] == 1
        assert parsed[("evolu_relay_request_ms_count", frozenset())] == 1
    finally:
        server.stop()
        db.close()


def test_multiprocess_relay_metrics_and_stats(tmp_path):
    relay = MultiprocessRelay(
        str(tmp_path / "relay.db"), workers=2, shards=4
    ).start()
    try:
        for i in range(8):
            _post(relay.url, _sync_req(f"mp-user-{i}", f"{i:016x}", n_msgs=2))
        # /metrics: any worker's exposition must parse as valid text.
        parsed = _parse_prometheus(_get(relay.url + "/metrics"))
        assert any(k[0] == "evolu_relay_requests_total" for k in parsed) or parsed == {}
        # /stats row counts come from the SHARED store: exact no matter
        # which worker answers (request counters are per-process and
        # are asserted only in the single-process test).
        stats = json.loads(_get(relay.url + "/stats"))
        assert stats["messages"] == 16
        assert stats["users"] == 8
        assert len(stats["shards"]) == 4
    finally:
        relay.stop()
