"""Device kernels vs. the CPU oracle: hashing, encoding, merge, Merkle.

The oracle modules (evolu_tpu.core.*, evolu_tpu.storage.apply) carry
the reference's exact semantics; every kernel must agree bit-for-bit.
"""

import random

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402 — guarded by the importorskip above

from evolu_tpu.core.merkle import (
    create_initial_merkle_tree,
    insert_into_merkle_tree,
    apply_prefix_xors,
    merkle_tree_to_string,
)
from evolu_tpu.core.murmur import murmur3_32
from evolu_tpu.core.timestamp import (
    Timestamp,
    timestamp_to_string,
    timestamp_to_hash,
)
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.ops.encode import (
    node_hex_to_u64,
    pack_ts_keys,
    render_timestamp_strings,
    timestamp_hashes,
)
from evolu_tpu.ops.hash import murmur3_32_batch
from evolu_tpu.ops.merge import plan_batch_device
from evolu_tpu.ops.merkle_ops import merkle_minute_deltas, minute_deltas_to_dict
from evolu_tpu.storage.apply import plan_batch


def _random_timestamps(rng, n, millis_range=(0, 2**43), nodes=None):
    out = []
    for _ in range(n):
        millis = rng.randrange(*millis_range)
        counter = rng.randrange(0, 65536)
        node = rng.choice(nodes) if nodes else f"{rng.getrandbits(64):016x}"
        out.append(Timestamp(millis, counter, node))
    return out


class TestDeviceHash:
    def test_matches_host_murmur_on_random_bytes(self):
        rng = random.Random(7)
        rows = [bytes(rng.randrange(256) for _ in range(46)) for _ in range(64)]
        batch = jnp.asarray(np.frombuffer(b"".join(rows), np.uint8).reshape(64, 46))
        got = np.asarray(murmur3_32_batch(batch))
        want = np.asarray([murmur3_32(r) for r in rows], np.uint32)
        np.testing.assert_array_equal(got, want)

    def test_various_lengths(self):
        rng = random.Random(8)
        for length in (1, 2, 3, 4, 5, 7, 13, 46):
            rows = [bytes(rng.randrange(256) for _ in range(length)) for _ in range(8)]
            batch = jnp.asarray(np.frombuffer(b"".join(rows), np.uint8).reshape(8, length))
            got = np.asarray(murmur3_32_batch(batch))
            want = np.asarray([murmur3_32(r) for r in rows], np.uint32)
            np.testing.assert_array_equal(got, want)


class TestDeviceEncode:
    def test_render_matches_host_string(self):
        rng = random.Random(9)
        ts = _random_timestamps(rng, 128) + [
            Timestamp(0, 0, "0000000000000000"),
            Timestamp(253402300799999, 65535, "ffffffffffffffff"),  # 9999-12-31
        ]
        millis = np.array([t.millis for t in ts], np.int64)
        counter = np.array([t.counter for t in ts], np.int32)
        node = np.array([node_hex_to_u64(t.node) for t in ts], np.uint64)
        rendered = np.asarray(render_timestamp_strings(millis, counter, node))
        for i, t in enumerate(ts):
            assert rendered[i].tobytes().decode("ascii") == timestamp_to_string(t)

    def test_device_hash_pipeline_matches_timestamp_to_hash(self):
        rng = random.Random(10)
        ts = _random_timestamps(rng, 64)
        millis = np.array([t.millis for t in ts], np.int64)
        counter = np.array([t.counter for t in ts], np.int32)
        node = np.array([node_hex_to_u64(t.node) for t in ts], np.uint64)
        got = np.asarray(timestamp_hashes(millis, counter, node))
        want = np.asarray([timestamp_to_hash(t) for t in ts], np.uint32)
        np.testing.assert_array_equal(got, want)

    def test_pack_keys_order_equals_string_order(self):
        rng = random.Random(11)
        ts = _random_timestamps(rng, 200, millis_range=(0, 10**6))
        millis = np.array([t.millis for t in ts], np.int64)
        counter = np.array([t.counter for t in ts], np.int32)
        k1 = np.asarray(pack_ts_keys(millis, counter))
        keys = [(int(k1[i]), node_hex_to_u64(ts[i].node)) for i in range(len(ts))]
        strings = [timestamp_to_string(t) for t in ts]
        assert sorted(range(len(ts)), key=lambda i: keys[i]) == sorted(
            range(len(ts)), key=lambda i: strings[i]
        )


class TestBlockedSegmentedScan:
    """The blocked two-level scan must be bit-identical to the
    associative_scan reference for every tiling shape, flag density,
    direction, and heavy key ties."""

    @pytest.mark.parametrize("n", [64, 128, 256, 1024, 1 << 14])
    @pytest.mark.parametrize("density", [0.0, 0.03, 0.5, 1.0])
    def test_matches_reference(self, n, density):
        import numpy as np

        from evolu_tpu.ops.merge import (
            _segmented_max_scan,
            _segmented_max_scan_reference,
        )

        rng = np.random.default_rng(n * 7 + int(density * 100))
        with jax.enable_x64(True):
            flags = rng.random(n) < density
            flags[0] = True
            k1 = rng.integers(0, 1 << 60, n).astype(np.uint64)
            k2 = rng.integers(0, 1 << 60, n).astype(np.uint64)
            k1[rng.random(n) < 0.3] = k1[0]  # tie stress
            for reverse in (False, True):
                f = flags if not reverse else np.append(flags[1:], True)
                ref = _segmented_max_scan_reference(
                    jnp.asarray(f), jnp.asarray(k1), jnp.asarray(k2), reverse
                )
                new = _segmented_max_scan(
                    jnp.asarray(f), jnp.asarray(k1), jnp.asarray(k2), reverse
                )
                assert np.array_equal(np.asarray(ref[0]), np.asarray(new[0]))
                assert np.array_equal(np.asarray(ref[1]), np.asarray(new[1]))

    def test_non_tiling_length_falls_back(self):
        import numpy as np

        from evolu_tpu.ops.merge import (
            _segmented_max_scan,
            _segmented_max_scan_reference,
        )

        with jax.enable_x64(True):
            n = 300  # not a multiple of the block
            rng = np.random.default_rng(4)
            flags = rng.random(n) < 0.1
            flags[0] = True
            k1 = rng.integers(0, 1 << 60, n).astype(np.uint64)
            k2 = rng.integers(0, 1 << 60, n).astype(np.uint64)
            ref = _segmented_max_scan_reference(jnp.asarray(flags), jnp.asarray(k1), jnp.asarray(k2))
            new = _segmented_max_scan(jnp.asarray(flags), jnp.asarray(k1), jnp.asarray(k2))
            assert np.array_equal(np.asarray(ref[0]), np.asarray(new[0]))
            assert np.array_equal(np.asarray(ref[1]), np.asarray(new[1]))


def _random_messages(rng, n, n_cells=10, nodes=None, millis_range=(0, 10**7)):
    cells = [
        (rng.choice(["todo", "todoCategory"]), f"row{i}", rng.choice(["title", "isDeleted"]))
        for i in range(n_cells)
    ]
    msgs = []
    for i in range(n):
        t = _random_timestamps(rng, 1, millis_range=millis_range, nodes=nodes)[0]
        table, row, col = rng.choice(cells)
        msgs.append(CrdtMessage(timestamp_to_string(t), table, row, col, f"v{i}"))
    return msgs


class TestDeviceMergePlanner:
    @pytest.mark.parametrize("seed", range(5))
    def test_matches_host_plan_batch(self, seed):
        rng = random.Random(seed)
        nodes = [f"{i:016x}" for i in range(1, 5)]
        msgs = _random_messages(rng, 97, n_cells=7, nodes=nodes)
        # Random existing winners for half the cells.
        existing = {}
        for cell in {(m.table, m.row, m.column) for m in msgs}:
            if rng.random() < 0.5:
                t = _random_timestamps(rng, 1, millis_range=(0, 10**7), nodes=nodes)[0]
                existing[cell] = timestamp_to_string(t)
        want_xor, want_upserts = plan_batch(msgs, existing)
        got_xor, got_upserts = plan_batch_device(msgs, existing)
        assert got_xor == want_xor
        # One upsert per cell; list order is unspecified (host emits
        # cell-first-touched order, device emits batch order).
        assert sorted(got_upserts, key=str) == sorted(want_upserts, key=str)
        assert len(got_upserts) == len({(m.table, m.row, m.column) for m in got_upserts})

    def test_duplicate_messages_xor_twice(self):
        # The reference quirk: re-received non-winning duplicates XOR again.
        t_old = timestamp_to_string(Timestamp(1000, 0, "0000000000000001"))
        t_win = timestamp_to_string(Timestamp(2000, 0, "0000000000000002"))
        msgs = [
            CrdtMessage(t_old, "todo", "r1", "title", "a"),
            CrdtMessage(t_old, "todo", "r1", "title", "a"),
        ]
        existing = {("todo", "r1", "title"): t_win}
        want = plan_batch(msgs, existing)
        got = plan_batch_device(msgs, existing)
        assert got[0] == want[0] == [True, True]
        assert got[1] == want[1] == []

    def test_high_contention_tiebreak(self):
        # 64 nodes writing the same cell at the same millis/counter:
        # winner must be the max node id (string order == node order).
        nodes = sorted(f"{random.Random(42).getrandbits(64):016x}" for _ in range(64))
        msgs = [
            CrdtMessage(
                timestamp_to_string(Timestamp(5000, 7, node)), "todo", "r", "title", node
            )
            for node in nodes
        ]
        want = plan_batch(msgs, {})
        got = plan_batch_device(msgs, {})
        assert got == want
        assert got[1][0].value == nodes[-1]


class TestDeviceMerkle:
    @pytest.mark.parametrize("seed", range(3))
    def test_batch_deltas_equal_sequential_inserts(self, seed):
        rng = random.Random(100 + seed)
        ts = _random_timestamps(rng, 150, millis_range=(0, 10**10))
        millis = np.array([t.millis for t in ts], np.int64)
        counter = np.array([t.counter for t in ts], np.int32)
        node = np.array([node_hex_to_u64(t.node) for t in ts], np.uint64)
        mask = np.array([rng.random() < 0.8 for t in ts], bool)

        deltas = minute_deltas_to_dict(*merkle_minute_deltas(millis, counter, node, mask))
        got = apply_prefix_xors(create_initial_merkle_tree(), deltas)

        want = create_initial_merkle_tree()
        for i, t in enumerate(ts):
            if bool(mask[i]):
                want = insert_into_merkle_tree(t, want)
        assert merkle_tree_to_string(got) == merkle_tree_to_string(want)

    def test_all_masked_minute_emits_nothing(self):
        millis = np.array([60000, 60000], np.int64)
        counter = np.array([0, 1], np.int32)
        node = np.array([1, 2], np.uint64)
        mask = np.array([False, False])
        deltas = minute_deltas_to_dict(*merkle_minute_deltas(millis, counter, node, mask))
        assert deltas == {}

    def test_tile_local_grouping_matches_sequential_inserts(self):
        """r4: at tiling lengths (N % 8192 == 0, N >= 16384) the
        grouping sort runs row-wise over (N/8192, 8192) tiles; a
        minute spanning tiles emits one partial delta per tile and the
        decoders XOR-merge them. End tree must equal sequential
        reference inserts — including minutes engineered to straddle
        tile junctions and equal keys meeting at a junction (which
        fuse back into one flat segment)."""
        from evolu_tpu.ops.merkle_ops import _GROUP_TILE

        rng = random.Random(77)
        n = 2 * _GROUP_TILE
        # Few distinct minutes ⇒ every minute spans both tiles; some
        # rows masked; a handful of distinct nodes.
        ts = []
        for i in range(n):
            millis = 60000 * rng.randrange(5) + rng.randrange(60000)
            ts.append(Timestamp(millis, rng.randrange(10), f"{rng.randrange(1, 50):016x}"))
        millis = np.array([t.millis for t in ts], np.int64)
        counter = np.array([t.counter for t in ts], np.int32)
        node = np.array([node_hex_to_u64(t.node) for t in ts], np.uint64)
        mask = np.array([rng.random() < 0.8 for _ in ts], bool)

        outs = merkle_minute_deltas(millis, counter, node, mask)
        # The tile path must actually have run: more raw seg-end rows
        # than distinct minutes proves block-local partials exist.
        ends = int((np.asarray(outs[1]) & np.asarray(outs[3])).sum())
        distinct = len({t.millis // 60000 for t, m in zip(ts, mask) if m})
        assert ends > distinct, "expected tile-local partial segments"

        got = apply_prefix_xors(create_initial_merkle_tree(), minute_deltas_to_dict(*outs))
        want = create_initial_merkle_tree()
        for i, t in enumerate(ts):
            if bool(mask[i]):
                want = insert_into_merkle_tree(t, want)
        assert merkle_tree_to_string(got) == merkle_tree_to_string(want)

    def test_tile_junction_fusion_all_valid(self):
        """All rows valid, ONE minute: each tile sorts to a single run
        of the same key, and the junction between tiles has equal keys
        on both sides — the flat boundary test must FUSE them (one
        segment, one seg_end) and the scan must carry across the
        reshape seam. A per-tile scan reset or a forced tile-start
        boundary would both fail here."""
        from evolu_tpu.core.murmur import to_int32
        from evolu_tpu.ops.merkle_ops import _GROUP_TILE

        n = 2 * _GROUP_TILE
        millis = np.full(n, 120000, np.int64)  # one minute, every row
        counter = np.arange(n, dtype=np.int32) % 16
        node = (np.arange(n, dtype=np.uint64) % 7) + 1
        mask = np.ones(n, bool)
        lo_s, seg_end, seg_xor, valid = merkle_minute_deltas(millis, counter, node, mask)
        ends = np.asarray(seg_end) & np.asarray(valid)
        assert int(ends.sum()) == 1, "equal keys at the junction must fuse"
        deltas = minute_deltas_to_dict(lo_s, seg_end, seg_xor, valid)
        want = 0
        for i in range(n):
            t = Timestamp(120000, int(counter[i]), f"{int(node[i]):016x}")
            want ^= timestamp_to_hash(t)
        assert list(deltas.values()) == [to_int32(want)]


class TestDevicePlannerEndState:
    def test_sqlite_end_state_matches_sequential_oracle(self):
        # Full pipeline: device planner driving real SQLite apply must
        # produce byte-identical end state vs. the reference loop.
        from tests.test_apply import make_db, dump, random_messages
        from evolu_tpu.storage import apply_messages
        from evolu_tpu.storage.apply import apply_messages_sequential

        for seed in (0, 1):
            rng = random.Random(1000 + seed)
            batches = [random_messages(rng, rng.randrange(1, 100)) for _ in range(3)]
            db_seq, db_dev = make_db(), make_db()
            tree_seq, tree_dev = {}, {}
            for batch in batches:
                tree_seq = apply_messages_sequential(db_seq, tree_seq, batch)
                tree_dev = apply_messages(db_dev, tree_dev, batch, planner=plan_batch_device)
            assert dump(db_seq) == dump(db_dev)
            assert tree_seq == tree_dev


def test_vectorized_timestamp_parse_matches_scalar():
    import random as _random

    import numpy as _np

    from evolu_tpu.core.timestamp import Timestamp, timestamp_from_string, timestamp_to_string
    from evolu_tpu.ops.host_parse import parse_timestamp_strings

    rng = _random.Random(21)
    stamps = []
    for _ in range(500):
        t = Timestamp(
            rng.randrange(0, 253_402_300_799_999),
            rng.randrange(0, 65536),
            f"{rng.getrandbits(64):016x}",
        )
        stamps.append(timestamp_to_string(t))
    millis, counter, node = parse_timestamp_strings(stamps)
    for i, s in enumerate(stamps):
        t = timestamp_from_string(s)
        assert (int(millis[i]), int(counter[i])) == (t.millis, t.counter), s
        assert f"{int(node[i]):016x}" == t.node, s


def test_vectorized_parse_rejects_malformed():
    import pytest as _pytest

    from evolu_tpu.core.types import TimestampParseError
    from evolu_tpu.ops.host_parse import parse_timestamp_strings

    good = "2024-01-15T10:30:00.123Z-0001-89e3b4f11a2c5d70"
    for bad in (
        "garbage",
        good.replace("T", " "),
        good.replace("-0001-", "-00g1-"),   # bad hex
        good[:-1] + "G",                     # bad node hex
        good.replace("10:30", "1a:30"),     # bad decimal
    ):
        with _pytest.raises(TimestampParseError):
            parse_timestamp_strings([good, bad])


def test_intern_cells_first_appearance_order():
    from evolu_tpu.ops.host_parse import intern_cells

    tables = ["t2", "t1", "t2", "t1", "t3"]
    rows = ["r", "r", "r", "r", "r"]
    cols = ["a", "a", "a", "b", "a"]
    cell_id, cells = intern_cells(tables, rows, cols)
    assert list(cell_id) == [0, 1, 0, 2, 3]
    assert cells == [("t2", "r", "a"), ("t1", "r", "a"), ("t1", "r", "b"), ("t3", "r", "a")]


def test_plan_batch_device_full_matches_python_deltas():
    from evolu_tpu.core.merkle import minutes_base3
    from evolu_tpu.core.murmur import to_int32
    from evolu_tpu.core.timestamp import timestamp_from_string, timestamp_to_hash
    from evolu_tpu.ops.merge import plan_batch_device, plan_batch_device_full

    from test_convergence import make_contention_workload

    messages = make_contention_workload(n_replicas=6, n_rows=9, writes_per_replica=10)
    xor_a, ups_a = plan_batch_device(messages, {})
    xor_b, ups_b, deltas = plan_batch_device_full(messages, {})
    assert xor_a == xor_b and ups_a == ups_b
    expect = {}
    for i, m in enumerate(messages):
        if xor_a[i]:
            t = timestamp_from_string(m.timestamp)
            k = minutes_base3(t.millis)
            expect[k] = to_int32(expect.get(k, 0) ^ timestamp_to_hash(t))
    assert deltas == expect


def test_non_canonical_hex_case_routes_to_host_oracle():
    """ADVICE r1 (medium): an uppercase-node wire timestamp is valid per
    the parser but the device kernel hashes a lowercased re-render and
    orders by numeric keys, both diverging from the reference's raw
    string semantics — e.g. nodes ABCDEF… and abcdef… parse to the SAME
    u64 yet are DIFFERENT timestamps under string order. Non-canonical
    batches must produce oracle-identical state on every entry point."""
    from test_apply import dump, make_db

    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.ops.host_parse import parse_timestamp_strings
    from evolu_tpu.ops.merge import plan_batch_device, plan_batch_device_full
    from evolu_tpu.storage.apply import apply_messages, apply_messages_sequential

    *_, case_ok = parse_timestamp_strings(
        ["2022-07-03T18:41:40.000Z-0000-" + "a" * 16,
         "2022-07-03T18:41:40.000Z-0000-ABCDEF0123456789",
         "2022-07-03T18:41:40.000Z-00ab-" + "a" * 16],
        with_case=True,
    )
    assert list(case_ok) == [True, False, False]

    row = "r" * 21
    msgs = [
        # Same millis/counter; same node u64, different node STRINGS.
        CrdtMessage("2022-07-03T18:41:40.000Z-0000-ABCDEF0123456789", "todo", row, "title", "U"),
        CrdtMessage("2022-07-03T18:41:40.000Z-0000-abcdef0123456789", "todo", row, "title", "L"),
        CrdtMessage("2022-07-03T18:41:41.000Z-0000-" + "b" * 16, "todo", row, "isCompleted", 1),
    ]
    for planner in (plan_batch_device, plan_batch_device_full):
        db_seq, db_dev = make_db(), make_db()
        tree_seq = apply_messages_sequential(db_seq, {}, msgs)
        tree_dev = apply_messages(db_dev, {}, msgs, planner=planner)
        assert dump(db_seq) == dump(db_dev)
        assert tree_seq == tree_dev


def test_server_deltas_non_canonical_owner_quarantined():
    """The relay hashes the parsed timestamp with node case verbatim
    (index.ts:155); an owner with non-canonical rows is quarantined to
    the host fold while canonical co-batched owners stay on device —
    the merged result must equal the reference fold for every owner."""
    from evolu_tpu.core.merkle import minute_deltas_host
    from evolu_tpu.parallel.mesh import create_mesh
    from evolu_tpu.server.engine import owner_minute_deltas

    rows = {
        "weird": ["2022-07-03T18:41:40.000Z-0000-ABCDEF0123456789",
                  "2022-07-03T18:41:40.000Z-0001-" + "c" * 16],
        "clean": [f"2022-07-03T18:4{i}:00.000Z-0000-" + "d" * 16 for i in range(4)],
    }
    deltas, digest = owner_minute_deltas(create_mesh(), rows)
    expect_digest = 0
    for o, ts_list in rows.items():
        expect, d = minute_deltas_host(ts_list)
        assert deltas[o] == expect, o
        expect_digest ^= d
    assert digest == expect_digest


def test_vectorized_parse_field_range_and_case_parity():
    import pytest as _pytest

    from evolu_tpu.core.types import TimestampParseError
    from evolu_tpu.ops.host_parse import parse_timestamp_strings

    good = "2024-01-15T10:30:00.123Z-0001-89e3b4f11a2c5d70"
    # Out-of-range fields must abort like the scalar datetime parser.
    for bad in (
        good.replace("2024-01", "2024-13"),
        good.replace("-15T", "-32T"),
        "2023-02-29T00:00:00.000Z-0001-89e3b4f11a2c5d70",  # not a leap year
        good.replace("T10", "T24"),
        good.replace(":30:", ":60:"),
    ):
        with _pytest.raises(TimestampParseError):
            parse_timestamp_strings([bad])
    # 2024 IS a leap year; Feb 29 parses.
    parse_timestamp_strings(["2024-02-29T00:00:00.000Z-0001-89e3b4f11a2c5d70"])
    # Mixed-case hex is non-canonical but must parse on every backend.
    m1, c1, n1 = parse_timestamp_strings([good.replace("0001", "00aB").replace("89e3", "89E3")])
    assert int(c1[0]) == 0xAB and f"{int(n1[0]):016x}".startswith("89e3")


def test_intern_cells_separator_injection_cannot_collide():
    from evolu_tpu.ops.host_parse import intern_cells

    cell_id, cells = intern_cells(["t", "t\x1fr"], ["r\x1fc", "c"], ["x", "x"])
    assert cell_id[0] != cell_id[1]
    assert len(cells) == 2


def test_vectorized_parse_rejects_year_zero():
    import pytest as _pytest

    from evolu_tpu.core.types import TimestampParseError
    from evolu_tpu.ops.host_parse import parse_timestamp_strings

    with _pytest.raises(TimestampParseError):
        parse_timestamp_strings(["0000-01-01T00:00:00.000Z-0000-" + "a" * 16])
    # Year 0001 is datetime's MINYEAR and must parse.
    parse_timestamp_strings(["0001-01-01T00:00:00.000Z-0000-" + "a" * 16])


def test_segmented_xor_scan_matches_reference():
    """The blocked segmented XOR scan (merkle_ops r3) must be
    bit-identical to the associative_scan reference across random
    segment shapes, including non-tiling lengths (fallback path)."""
    import jax.numpy as jnp

    from evolu_tpu.ops.merkle_ops import (
        segmented_xor_scan,
        segmented_xor_scan_reference,
    )

    rng = np.random.default_rng(9)
    for n in (1, 255, 256, 4096, 70000):
        flags = rng.random(n) < 0.05
        flags[0] = True
        v = rng.integers(0, 2**32, n, dtype=np.uint32)
        exp = segmented_xor_scan_reference(jnp.asarray(flags), jnp.asarray(v))
        got = segmented_xor_scan(jnp.asarray(flags), jnp.asarray(v))
        assert (np.asarray(exp) == np.asarray(got)).all(), n


def test_flags_kernel_matches_payload_kernel():
    """The r5 production kernel (`plan_merge_sorted_flags`: stored-winner
    relations as two flag bits in the sort key, 2 u64 payloads) must be
    BIT-identical to the payload core on adversarial shapes — exact key
    ties, e==s, zero keys, heavy cell contention, padding rows, and an
    extras payload — since both `_plan_full_kernel` and the sharded
    reconcile now route through it."""
    import jax.numpy as jnp

    from evolu_tpu.ops.merge import (
        _PAD_CELL,
        plan_merge_sorted_core,
        plan_merge_sorted_flags,
    )

    old_j = jax.jit(lambda *a: plan_merge_sorted_core(*a[:5], extras=(a[5],)))
    new_j = jax.jit(lambda *a: plan_merge_sorted_flags(*a[:5], extras=(a[5],)))
    rng = np.random.default_rng(17)
    N = 1024
    with jax.enable_x64(True):
        for trial in range(25):
            n = int(rng.integers(4, N))
            cells = int(rng.integers(1, max(2, n // 2)))
            cell = np.full(N, int(_PAD_CELL), np.int32)
            cell[:n] = rng.integers(0, cells, n)
            # Tiny key range → many exact ties, e==s rows, p>s runs.
            k1 = np.zeros(N, np.uint64)
            k2 = np.zeros(N, np.uint64)
            k1[:n] = rng.integers(0, 6, n)
            k2[:n] = rng.integers(0, 4, n)
            ex1 = np.zeros(N, np.uint64)
            ex2 = np.zeros(N, np.uint64)
            has = rng.random(cells) < 0.7
            ex1_c = np.where(has, rng.integers(0, 6, cells), 0).astype(np.uint64)
            ex2_c = np.where(has, rng.integers(0, 4, cells), 0).astype(np.uint64)
            ex1[:n] = ex1_c[cell[:n]]
            ex2[:n] = ex2_c[cell[:n]]
            owner = rng.integers(0, 64, N).astype(np.int32)
            args = tuple(map(jnp.asarray, (cell, k1, k2, ex1, ex2, owner)))
            old = old_j(*args)
            new = new_j(*args)
            for j in range(5):
                assert np.array_equal(np.asarray(old[j]), np.asarray(new[j])), (trial, j)
            assert np.array_equal(np.asarray(old[5][0]), np.asarray(new[5][0])), trial


def test_millis_u32_fast_path_matches_i64_at_boundaries():
    """The r5 u32 divmod chain in the hash render must be bit-identical
    to the exact int64 path across its `lax.cond` boundary: in-range
    batches (fast path), pre-1970 and post-2106 batches (exact path),
    and batches STRADDLING the boundary (whole batch exact)."""
    import jax.numpy as jnp

    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_hash
    from evolu_tpu.ops.encode import timestamp_hashes, u64_to_node_hex

    from evolu_tpu.ops.merkle_ops import js_minutes

    bound = 1000 << 32  # first out-of-fast-range milli (2106-02-07)
    shapes = {
        "in_range": np.array([0, 999, 1000, 86_400_000 - 1, 1_700_000_000_000,
                              bound - 1], np.int64),
        "far_future": np.array([bound, bound + 12345, 250_000_000_000_000], np.int64),
        "pre_epoch": np.array([-1, -86_400_000, -62_135_596_800_000 + 86_400_000], np.int64),
        "straddling": np.array([0, bound - 1, bound, 1_700_000_000_000], np.int64),
    }
    with jax.enable_x64(True):
        for name, millis in shapes.items():
            n = len(millis)
            counter = np.arange(n, dtype=np.int32) * 7 % 65536
            node = (np.arange(n, dtype=np.uint64) * 0x9E3779B97F4A7C15 | 1)
            got = np.asarray(timestamp_hashes(
                jnp.asarray(millis), jnp.asarray(counter.astype(np.int32)),
                jnp.asarray(node),
            ))
            # The minute stage shares the u32 divmod chain — pin it at
            # the same boundaries against the exact i64 division.
            got_min = np.asarray(jax.jit(js_minutes)(jnp.asarray(millis)))
            assert np.array_equal(
                got_min, (millis // 60000).astype(np.int32)
            ), name
            for i in range(n):
                want = timestamp_to_hash(
                    Timestamp(int(millis[i]), int(counter[i]),
                              u64_to_node_hex(int(node[i])))
                ) & 0xFFFFFFFF
                assert int(got[i]) == want, (name, i, int(millis[i]))


def test_u32_divmod_overflow_guard_is_a_real_exception():
    """The intermediate-overflow precondition of `u32_divmod_hi_lo`
    must raise ValueError — not assert — so the guard survives
    `python -O` (ADVICE r5). 86_400_000 is the canonical offender:
    r32 = 61_367_296, and 999·r32 + (d-1) overflows u32."""
    import numpy as np
    import pytest

    from evolu_tpu.ops.encode import u32_divmod_hi_lo

    with pytest.raises(ValueError, match="overflows the u32 chain"):
        u32_divmod_hi_lo(np.zeros(4, np.int64), 86_400_000)
