"""The fused receive leg (r5): `ehc_decrypt_response_columns` →
PackedReceive → packed plan (`plan_packed`) → `eh_apply_planned_cells`.

Reference path being replaced, as ONE leg:
packages/evolu/src/sync.worker.ts:135-173 → receive.ts:144 →
applyMessages.ts:78. The invariant throughout: the packed path either
produces EXACTLY the object path's outcome (state, clock, errors) or
bounces to the object path before any side effect.
"""

import random

import numpy as np
import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.storage.apply import apply_messages
from evolu_tpu.storage.native import native_available, open_database
from evolu_tpu.storage.schema import init_db_model
from evolu_tpu.sync import native_crypto, protocol
from evolu_tpu.sync.client import encrypt_messages
from evolu_tpu.utils.config import Config

MN = "legal winner thank year wave sausage worth useful legal winner thank yellow"

pytestmark = pytest.mark.skipif(
    not native_crypto.native_available(), reason="native crypto unavailable"
)


def _mk_msgs(n=400, seed=11, nodes=("a1b2c3d4e5f60718", "ffeeddccbbaa9988")):
    rng = random.Random(seed)
    vals = [
        lambda i: f"título {i} ✓",
        lambda i: i % 2,
        lambda i: None,
        lambda i: i * 0.25,
        lambda i: "x\x00y",  # NUL-bearing value must round-trip
        lambda i: -(2**63) if i % 2 else 2**63 - 1,
        lambda i: "",
    ]
    out = []
    for i in range(n):
        out.append(
            CrdtMessage(
                timestamp_to_string(
                    Timestamp(
                        1_700_000_000_000 + (i // 3) * 977, i % 3, rng.choice(nodes)
                    )
                ),
                rng.choice(["todo", "todoCategory"]),
                f"row{rng.randrange(n // 5 or 1)}",
                rng.choice(["title", "isCompleted"]),
                vals[i % len(vals)](i),
            )
        )
    rng.shuffle(out)
    return out


def _response_bytes(msgs, tree='{"m":1}'):
    enc = encrypt_messages(msgs, MN)
    return protocol.encode_sync_response(protocol.SyncResponse(tuple(enc), tree))


def test_columns_materialization_matches_object_path():
    """decrypt_response_columns must reproduce the object path exactly:
    same messages (incl. NUL/unicode/int64-extreme values), same tree,
    and interning must preserve first-appearance semantics."""
    msgs = _mk_msgs(120)
    resp = _response_bytes(msgs)
    out = native_crypto.decrypt_response_columns(resp, MN)
    assert out is not None
    pb, tree = out
    obj = native_crypto.decrypt_response(resp, MN)
    assert pb.to_messages() == obj[0] == tuple(msgs)
    assert tree == obj[1] == '{"m":1}'
    # Cell interning matches the host interner (first appearance).
    from evolu_tpu.ops.host_parse import intern_cells

    cid, cells = intern_cells(
        [m.table for m in msgs], [m.row for m in msgs], [m.column for m in msgs]
    )
    assert cells == pb.cells
    assert np.array_equal(cid, pb.cell_id)
    # Slices materialize their exact row range.
    assert pb[10:37].to_messages() == tuple(msgs[10:37])


def test_columns_fallbacks_to_object_path():
    """Every non-canonical shape returns None BEFORE any output: a
    demoted ciphertext (gpg-compressed), wrong password, truncated
    wire, a non-46-byte timestamp, and invalid UTF-8 inside decrypted
    content. The object/pure chain then owns the exact error."""
    from pathlib import Path

    msgs = _mk_msgs(8)
    enc = list(native_crypto.encrypt_batch(msgs, MN))
    fixtures = Path(__file__).parent / "fixtures"
    gpg_ct = (fixtures / "gpg_aes256_s2k1024_zip.pgp").read_bytes()
    ts46 = msgs[0].timestamp
    spliced = list(enc)
    spliced.insert(3, protocol.EncryptedCrdtMessage(ts46, gpg_ct))
    resp = protocol.encode_sync_response(protocol.SyncResponse(tuple(spliced), "{}"))
    assert native_crypto.decrypt_response_columns(resp, MN) is None
    # ...but the object path still serves it (oracle demotion).
    assert native_crypto.decrypt_response(resp, MN) is not None

    ok = protocol.encode_sync_response(protocol.SyncResponse(tuple(enc), "{}"))
    assert native_crypto.decrypt_response_columns(ok, "wrong-pw") is None
    assert native_crypto.decrypt_response_columns(ok[:-1], MN) is None

    short_ts = list(enc)
    short_ts[2] = protocol.EncryptedCrdtMessage("short-ts", short_ts[2].content)
    resp = protocol.encode_sync_response(protocol.SyncResponse(tuple(short_ts), "{}"))
    assert native_crypto.decrypt_response_columns(resp, MN) is None
    assert native_crypto.decrypt_response(resp, MN) is not None

    # Invalid UTF-8 inside a decrypted string field: the pure path
    # raises (ValueError family); columns must bounce, not emit bytes
    # Python would reject.
    from evolu_tpu.sync.crypto import encrypt_symmetric

    bad_content = b"\x0a\x02t\xff" + b"\x12\x01r" + b"\x1a\x01c"
    bad = protocol.EncryptedCrdtMessage(ts46, encrypt_symmetric(bad_content, MN))
    resp = protocol.encode_sync_response(protocol.SyncResponse((bad,), "{}"))
    assert native_crypto.decrypt_response_columns(resp, MN) is None
    with pytest.raises(ValueError):
        msgs_out = native_crypto.decrypt_response(resp, MN)
        if msgs_out is None:  # pure-path ownership
            from evolu_tpu.sync.client import decrypt_messages

            decrypt_messages(
                protocol.decode_sync_response(resp).messages, MN
            )


@pytest.mark.skipif(not native_available(), reason="native host unavailable")
def test_packed_apply_state_equals_object_apply():
    """The full fused leg vs the object leg, same response bytes, two
    fresh databases: identical __message rows, app-table rows, and
    Merkle tree — including a second wave on top of stored winners and
    chunked slices."""
    from evolu_tpu.runtime.worker import select_planner

    msgs = _mk_msgs(2000, seed=3)
    resp = _response_bytes(msgs)
    pb, _tree = native_crypto.decrypt_response_columns(resp, MN)

    def mkdb():
        db = open_database(backend="auto")
        init_db_model(db, mnemonic=None)
        for t in ("todo", "todoCategory"):
            db.exec(
                f'CREATE TABLE "{t}" ("id" TEXT PRIMARY KEY, "title" BLOB, '
                '"isCompleted" BLOB)'
            )
        return db

    def dump(db):
        return (
            db.exec_sql_query(
                'SELECT * FROM "__message" ORDER BY "timestamp","table","row","column"',
                (),
            ),
            db.exec_sql_query('SELECT * FROM "todo" ORDER BY "id"', ()),
            db.exec_sql_query('SELECT * FROM "todoCategory" ORDER BY "id"', ()),
        )

    results = {}
    for mode in ("objects", "packed"):
        db = mkdb()
        planner = select_planner(Config(min_device_batch=64), db)
        half = len(msgs) // 2
        b1 = tuple(msgs[:half]) if mode == "objects" else pb[:half]
        b2 = tuple(msgs[half:]) if mode == "objects" else pb[half:]
        t1 = apply_messages(db, {}, b1, planner=planner)
        t2 = apply_messages(db, t1, b2, planner=planner)
        results[mode] = (dump(db), t2)
        db.close()
    assert results["objects"] == results["packed"]


@pytest.mark.skipif(not native_available(), reason="native host unavailable")
def test_packed_noncanonical_case_routes_to_host_oracle():
    """Uppercase node hex is non-canonical: the packed planner must
    bounce (None) and the materialized object path's host oracle must
    produce the reference's raw-string-order state — equal to the
    pure-Python backend applying the same messages."""
    from evolu_tpu.runtime.worker import select_planner

    msgs = _mk_msgs(1500, seed=9, nodes=("A1B2C3D4E5F60718", "ffeeddccbbaa9988"))
    resp = _response_bytes(msgs)
    pb, _tree = native_crypto.decrypt_response_columns(resp, MN)
    assert pb is not None  # ASCII case parses; canonicality is a PLAN concern
    _m, _c, _n, case_ok = pb.parse_timestamps()
    assert not bool(case_ok.all())

    def mk(backend):
        db = open_database(backend=backend)
        init_db_model(db, mnemonic=None)
        for t in ("todo", "todoCategory"):
            db.exec(
                f'CREATE TABLE "{t}" ("id" TEXT PRIMARY KEY, "title" BLOB, '
                '"isCompleted" BLOB)'
            )
        return db

    db_packed = mk("auto")
    planner = select_planner(Config(min_device_batch=64), db_packed)
    assert planner.plan_packed(pb) is None
    tree_packed = apply_messages(db_packed, {}, pb, planner=planner)

    db_pure = mk("python")
    tree_pure = apply_messages(db_pure, {}, tuple(msgs))
    q = 'SELECT * FROM "__message" ORDER BY "timestamp","table","row","column"'
    assert db_packed.exec_sql_query(q, ()) == db_pure.exec_sql_query(q, ())
    assert tree_packed == tree_pure
    db_packed.close(), db_pure.close()


def test_fuzz_columns_never_diverges_from_oracle():
    """Mutation fuzz over response bytes: whenever the columns walker
    accepts the wire, its materialization must equal the pure
    decode+decrypt value exactly. (Columns never accepts an erroring
    wire — any demotion is a None — so an accepted wire implies the
    oracle succeeds too.)"""
    from evolu_tpu.sync.client import decrypt_messages
    from evolu_tpu.sync.crypto import PgpError

    rng = random.Random(29)
    base = _response_bytes(_mk_msgs(6), tree='{"x":1}')
    accepted = 0
    for trial in range(200):
        b = bytearray(base)
        for _ in range(rng.randint(1, 5)):
            op = rng.random()
            if op < 0.6 and b:
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            elif op < 0.8 and len(b) > 2:
                del b[rng.randrange(len(b))]
            else:
                b.insert(rng.randrange(len(b) + 1), rng.randrange(256))
        data = bytes(b)
        out = native_crypto.decrypt_response_columns(data, MN)
        if out is None:
            continue  # production falls through to the object/pure chain
        accepted += 1
        pb, tree = out
        try:
            resp = protocol.decode_sync_response(data)
            oracle = (decrypt_messages(resp.messages, MN), resp.merkle_tree)
        except (PgpError, ValueError) as e:  # pragma: no cover - divergence
            raise AssertionError(
                f"columns accepted a wire the oracle rejects ({e!r}), trial {trial}"
            )
        assert (pb.to_messages(), tree) == oracle, f"trial {trial}"
    assert accepted  # the fuzz must exercise the accept path at least once


@pytest.mark.skipif(not native_available(), reason="native host unavailable")
def test_packed_streaming_and_nocache_routes_match_oracle():
    """The two packed plan routes that do NOT use HBM-cached winners —
    the adaptive gate's STREAMING mode and a `winner_cache=False`
    deployment — are production-routed and must equal the pure-Python
    oracle's state exactly (they share `plan_packed_streamed`, but each
    entry point is exercised here on purpose)."""
    from evolu_tpu.runtime.worker import select_planner

    msgs = _mk_msgs(1500, seed=31)
    resp = _response_bytes(msgs)
    pb, _tree = native_crypto.decrypt_response_columns(resp, MN)
    q = 'SELECT * FROM "__message" ORDER BY "timestamp","table","row","column"'

    def mk(backend):
        db = open_database(backend=backend)
        init_db_model(db, mnemonic=None)
        for t in ("todo", "todoCategory"):
            db.exec(
                f'CREATE TABLE "{t}" ("id" TEXT PRIMARY KEY, "title" BLOB, '
                '"isCompleted" BLOB)'
            )
        return db

    db_oracle = mk("python")
    tree_oracle = apply_messages(db_oracle, {}, tuple(msgs))
    want = db_oracle.exec_sql_query(q, ())

    # (a) winner_cache off → worker._plan_packed_streamed_nocache.
    db_a = mk("auto")
    planner_a = select_planner(
        Config(min_device_batch=64, winner_cache=False), db_a
    )
    assert getattr(planner_a, "cache", None) is None
    tree_a = apply_messages(db_a, {}, pb, planner=planner_a)
    assert db_a.exec_sql_query(q, ()) == want and tree_a == tree_oracle

    # (b) adaptive streaming mode → DeviceWinnerCache._plan_packed_streamed.
    db_b = mk("auto")
    planner_b = select_planner(Config(min_device_batch=64), db_b)
    cache = planner_b.cache
    cache._streaming = True
    cache._known = set()
    cache._seed_ewma = 1.0  # above seed_lo: the gate stays streaming
    tree_b = apply_messages(db_b, {}, pb, planner=planner_b)
    assert cache._streaming, "the gate left streaming mode unexpectedly"
    assert db_b.exec_sql_query(q, ()) == want and tree_b == tree_oracle
    db_oracle.close(), db_a.close(), db_b.close()


@pytest.mark.skipif(not native_available(), reason="native host unavailable")
def test_worker_receive_packed_equals_objects():
    """DbWorker._receive fed the SAME response as PackedReceive vs
    CrdtMessage tuple: identical database state, clock, and outputs —
    and identical HLC error surfaces (duplicate node)."""
    from evolu_tpu.runtime import messages as rmsg
    from evolu_tpu.runtime.worker import DbWorker

    msgs = _mk_msgs(1600, seed=21)
    resp = _response_bytes(msgs, tree="{}")
    pb, tree = native_crypto.decrypt_response_columns(resp, MN)

    def run(batch):
        db = open_database(backend="auto")
        outputs = []
        worker = DbWorker(
            db,
            Config(min_device_batch=64),
            on_output=outputs.append,
            now=lambda: 1_700_001_000_000,  # past every message: no drift error
        )
        worker.start(mnemonic=MN)
        for t in ("todo", "todoCategory"):
            db.exec(
                f'CREATE TABLE IF NOT EXISTS "{t}" ("id" TEXT PRIMARY KEY, '
                '"title" BLOB, "isCompleted" BLOB)'
            )
        worker.post(rmsg.Receive(batch, tree, None))
        worker.flush()
        state = (
            db.exec_sql_query(
                'SELECT * FROM "__message" ORDER BY "timestamp","table","row","column"',
                (),
            ),
            db.exec_sql_query('SELECT * FROM "todo" ORDER BY "id"', ()),
            # Clock WITHOUT the node suffix: the node id is random per
            # device, so only millis/counter and the tree must match.
            [
                (r["timestamp"][:29], r["merkleTree"])
                for r in db.exec_sql_query(
                    'SELECT "timestamp", "merkleTree" FROM "__clock"', ()
                )
            ],
        )
        kinds = [type(o).__name__ for o in outputs]
        worker.stop()
        db.close()
        return state, kinds

    s_obj, k_obj = run(tuple(msgs))
    s_pk, k_pk = run(pb)
    assert s_obj == s_pk
    assert s_obj[0], "no rows applied — the receive leg never ran"
    assert k_obj == k_pk


@pytest.mark.skipif(not native_available(), reason="native host unavailable")
def test_packed_typed_cells_bounce_before_side_effects():
    """ISSUE 7 satellite: ANY typed cell in a packed batch routes to
    the object path BEFORE side effects (the r5 packed-receive
    contract extended to CRDT column types) — the packed C cell-apply
    would LWW-upsert raw op values, and the typed fold needs message
    objects. Pinned: plan_packed is NEVER consulted, the bounce
    counter moves, and the end state equals the pure object path."""
    from evolu_tpu.core import crdt_list as cl
    from evolu_tpu.core import crdt_types as ct
    from evolu_tpu.obs import metrics
    from evolu_tpu.runtime.worker import select_planner
    from evolu_tpu.storage.schema import update_db_schema
    from evolu_tpu.core.types import TableDefinition

    rng = random.Random(21)
    base = 1_700_000_000_000
    msgs = []
    elem_pool = []
    for i in range(300):
        ts = timestamp_to_string(
            Timestamp(base + i * 977, i % 3, "a1b2c3d4e5f60718"))
        roll = rng.random()
        row = f"row{rng.randrange(20)}"
        if roll < 0.3:
            msgs.append(CrdtMessage(ts, "todo", row, "votes",
                                    rng.randrange(-9, 10)))
        elif roll < 0.5:
            msgs.append(CrdtMessage(ts, "todo", row, "labels",
                                    ct.set_add_value(rng.choice("xyz"))))
        elif roll < 0.65:
            after = rng.choice(elem_pool) if elem_pool and rng.random() < 0.7 \
                else None
            msgs.append(CrdtMessage(ts, "todo", row, "notes",
                                    cl.list_insert_value(f"n{i}", after=after)))
            elem_pool.append(ts)
        elif roll < 0.72 and elem_pool:
            msgs.append(CrdtMessage(ts, "todo", row, "notes",
                                    cl.list_delete_value(rng.choice(elem_pool))))
        else:
            msgs.append(CrdtMessage(ts, "todo", row, "title", f"t{i}"))
    resp = _response_bytes(msgs)
    pb, _tree = native_crypto.decrypt_response_columns(resp, MN)
    assert pb is not None

    def mkdb():
        db = open_database(backend="auto")
        init_db_model(db, mnemonic=None)
        update_db_schema(db, [TableDefinition.of(
            "todo", ("title", "votes:counter", "labels:awset", "notes:list"))])
        return db

    def dump(db):
        return (
            db.exec_sql_query(
                'SELECT * FROM "__message" ORDER BY "timestamp","table","row","column"',
                (),
            ),
            db.exec_sql_query('SELECT * FROM "todo" ORDER BY "id"', ()),
            db.exec_sql_query('SELECT * FROM "__crdt_counter" ORDER BY "row","column"', ()),
            db.exec_sql_query('SELECT * FROM "__crdt_set" ORDER BY "tag"', ()),
            db.exec_sql_query('SELECT * FROM "__crdt_list" ORDER BY "tag"', ()),
            db.exec_sql_query('SELECT * FROM "__crdt_list_kill" ORDER BY "tag"', ()),
        )

    results = {}
    for mode in ("objects", "packed"):
        db = mkdb()
        planner = select_planner(Config(min_device_batch=64), db)
        calls = []
        orig = planner.plan_packed
        planner.plan_packed = lambda p: (calls.append(1), orig(p))[1]
        before = metrics.get_counter("evolu_crdt_packed_bounces_total")
        batch = tuple(msgs) if mode == "objects" else pb
        tree = apply_messages(db, {}, batch, planner=planner)
        if mode == "packed":
            assert not calls, "plan_packed ran on a typed batch"
            assert metrics.get_counter(
                "evolu_crdt_packed_bounces_total") == before + 1
        results[mode] = (dump(db), tree)
        db.close()
    assert results["objects"] == results["packed"]


def test_packed_tensor_cells_bounce_before_side_effects():
    """ISSUE 20 satellite: tensor cells in a packed batch take the
    SAME pre-side-effect bounce as the other typed families — the
    packed C cell-apply would LWW-upsert the raw op JSON where the
    semidirect fold needs message objects. Pinned exactly like the
    ISSUE 7 leg: plan_packed never consulted, the bounce counter
    moves, end state equals the pure object path bit-for-bit."""
    from evolu_tpu.core import crdt_tensor as tz
    from evolu_tpu.core.types import TableDefinition
    from evolu_tpu.obs import metrics
    from evolu_tpu.runtime.worker import select_planner
    from evolu_tpu.storage.schema import update_db_schema

    cfg_sum = tz.parse_tensor_type("tensor:sum:f32:2")
    cfg_max = tz.parse_tensor_type("tensor:max:bf16:3")
    rng = random.Random(20)
    base = 1_700_000_000_000
    msgs = []
    for i in range(200):
        ts = timestamp_to_string(
            Timestamp(base + i * 977, i % 3, "a1b2c3d4e5f60718"))
        roll = rng.random()
        row = f"row{rng.randrange(8)}"
        if roll < 0.35:
            vals = [rng.uniform(-20, 20), rng.uniform(-20, 20)]
            mk = tz.tensor_set_value if rng.random() < 0.3 \
                else tz.tensor_delta_value
            msgs.append(CrdtMessage(ts, "todo", row, "weights",
                                    mk(cfg_sum, vals)))
        elif roll < 0.55:
            vals = [rng.uniform(-8, 8) for _ in range(3)]
            msgs.append(CrdtMessage(ts, "todo", row, "peak",
                                    tz.tensor_delta_value(cfg_max, vals)))
        elif roll < 0.62:  # malformed tensor traffic rides along
            msgs.append(CrdtMessage(ts, "todo", row, "weights",
                                    rng.choice(["junk", '["d","x!"]'])))
        else:
            msgs.append(CrdtMessage(ts, "todo", row, "title", f"t{i}"))
    resp = _response_bytes(msgs)
    pb, _tree = native_crypto.decrypt_response_columns(resp, MN)
    assert pb is not None

    def mkdb():
        db = open_database(backend="auto")
        init_db_model(db, mnemonic=None)
        update_db_schema(db, [TableDefinition.of(
            "todo",
            ("title", "weights:tensor:sum:f32:2", "peak:tensor:max:bf16:3"))])
        return db

    def dump(db):
        return (
            db.exec_sql_query(
                'SELECT * FROM "__message" '
                'ORDER BY "timestamp","table","row","column"', ()),
            db.exec_sql_query('SELECT * FROM "todo" ORDER BY "id"', ()),
            db.exec_sql_query(
                'SELECT * FROM "__crdt_tensor" ORDER BY "tag","column"', ()),
        )

    results = {}
    for mode in ("objects", "packed"):
        db = mkdb()
        planner = select_planner(Config(min_device_batch=64), db)
        calls = []
        orig = planner.plan_packed
        planner.plan_packed = lambda p: (calls.append(1), orig(p))[1]
        before = metrics.get_counter("evolu_crdt_packed_bounces_total")
        batch = tuple(msgs) if mode == "objects" else pb
        tree = apply_messages(db, {}, batch, planner=planner)
        if mode == "packed":
            assert not calls, "plan_packed ran on a tensor batch"
            assert metrics.get_counter(
                "evolu_crdt_packed_bounces_total") == before + 1
        results[mode] = (dump(db), tree)
        db.close()
    assert results["objects"] == results["packed"]
