"""Pallas timestamp-hash kernel: bit-exact vs oracle and XLA path.

Runs the kernel in interpreter mode (CPU test env); the driver's TPU
bench exercises the compiled path.
"""

import numpy as np
import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_hash
from evolu_tpu.ops.encode import timestamp_hashes
from evolu_tpu.ops.pallas_hash import PALLAS_AVAILABLE, timestamp_hashes_pallas

pytestmark = pytest.mark.skipif(not PALLAS_AVAILABLE, reason="pallas unavailable")


def _batch(n=300, seed=3):
    rng = np.random.default_rng(seed)
    millis = 1_700_000_000_000 + rng.integers(0, 365 * 86_400_000, n).astype(np.int64)
    counter = rng.integers(0, 65536, n).astype(np.int32)
    node = rng.integers(0, 2**64, n, dtype=np.uint64)
    return millis, counter, node


def test_pallas_matches_xla_path():
    millis, counter, node = _batch()
    got = np.asarray(timestamp_hashes_pallas(millis, counter, node, interpret=True))
    want = np.asarray(timestamp_hashes(millis, counter, node))
    np.testing.assert_array_equal(got, want)


def test_pallas_matches_host_oracle():
    millis, counter, node = _batch(64, seed=9)
    got = np.asarray(timestamp_hashes_pallas(millis, counter, node, interpret=True))
    for i in range(len(millis)):
        t = Timestamp(int(millis[i]), int(counter[i]), f"{int(node[i]):016x}")
        assert int(got[i]) == timestamp_to_hash(t) & 0xFFFFFFFF, i


def test_pallas_edge_dates_and_padding():
    # Epoch boundary, leap day, century/leap-year rules, year 9999; and a
    # deliberately non-tile-aligned batch length.
    cases = [
        0,
        951_782_400_000,        # 2000-02-29
        4_107_542_399_000,      # 2100-02-28 end of day (2100 not a leap year)
        253_402_300_799_999,    # 9999-12-31T23:59:59.999
    ]
    millis = np.array(cases * 13, np.int64)[:50]
    counter = np.arange(50, dtype=np.int32) % 65536
    node = (np.arange(50, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15))
    got = np.asarray(timestamp_hashes_pallas(millis, counter, node, interpret=True))
    want = np.asarray(timestamp_hashes(millis, counter, node))
    np.testing.assert_array_equal(got, want)


def test_pallas_segmented_scan_matches_reference():
    """The single-pass Pallas segmented lex-max scan must be
    bit-identical to merge._segmented_max_scan_reference across random
    segment shapes, forward and reverse, including cross-block
    segments (N spans several grid steps) and all-zero/sentinel keys."""
    import jax
    from evolu_tpu.ops.merge import _segmented_max_scan_reference
    from evolu_tpu.ops.pallas_scan import segmented_max_scan_pallas

    rng = np.random.default_rng(5)
    with jax.enable_x64(True):
        for n in (1, 127, 128, 4096, 70000):
            flags = rng.random(n) < 0.03
            flags[0] = True
            k1 = rng.integers(0, 2**64, n, dtype=np.uint64)
            k2 = rng.integers(0, 2**64, n, dtype=np.uint64)
            # Ties in k1 (forces the k2 limb compare) and zero keys.
            k1[rng.random(n) < 0.3] = np.uint64(42) << np.uint64(32)
            k1[rng.random(n) < 0.1] = 0
            k2[rng.random(n) < 0.1] = 0
            for reverse in (False, True):
                f = flags if not reverse else np.roll(flags, -1)  # ends
                exp1, exp2 = _segmented_max_scan_reference(
                    jax.numpy.asarray(f), jax.numpy.asarray(k1),
                    jax.numpy.asarray(k2), reverse=reverse,
                )
                got1, got2 = segmented_max_scan_pallas(
                    jax.numpy.asarray(f), jax.numpy.asarray(k1),
                    jax.numpy.asarray(k2), reverse=reverse, interpret=True,
                )
                assert (np.asarray(exp1) == np.asarray(got1)).all(), (n, reverse)
                assert (np.asarray(exp2) == np.asarray(got2)).all(), (n, reverse)


def test_pallas_segmented_xor_scan_matches_reference():
    """The single-pass Pallas segmented XOR scan must be bit-identical
    to the associative_scan reference, including cross-block segments."""
    import jax
    from evolu_tpu.ops.merkle_ops import segmented_xor_scan_reference
    from evolu_tpu.ops.pallas_scan import segmented_xor_scan_pallas

    rng = np.random.default_rng(10)
    for n in (1, 4096, 70000):
        flags = rng.random(n) < 0.02
        flags[0] = True
        v = rng.integers(0, 2**32, n, dtype=np.uint32)
        exp = segmented_xor_scan_reference(jax.numpy.asarray(flags), jax.numpy.asarray(v))
        got = segmented_xor_scan_pallas(jax.numpy.asarray(flags), jax.numpy.asarray(v), interpret=True)
        assert (np.asarray(exp) == np.asarray(got)).all(), n
