"""Mesh-sharded reconcile tests on the virtual 8-device CPU mesh.

Config-5 shape (SURVEY.md §6): owners sharded over a mesh, per-owner
results identical to the host oracle, digests XOR-combined across
devices.
"""

import numpy as np
import pytest

import jax

from evolu_tpu.core.merkle import create_initial_merkle_tree, apply_prefix_xors
from evolu_tpu.core.timestamp import (
    create_initial_timestamp,
    send_timestamp,
    timestamp_to_hash,
    timestamp_from_string,
    timestamp_to_string,
)
from evolu_tpu.core.murmur import to_int32
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.parallel import (
    assign_owners_to_shards,
    create_mesh,
    reconcile_owner_batches,
)
from evolu_tpu.storage.apply import plan_batch


def _mk_messages(node, n, start_millis=1_700_000_000_000, table="todo", rows=8):
    t = create_initial_timestamp(node)
    out = []
    for i in range(n):
        t = send_timestamp(t, start_millis + i * 7)
        out.append(
            CrdtMessage(
                timestamp_to_string(t), table, f"row{i % rows}", "title", f"v{i}"
            )
        )
    return out


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_assign_owners_balanced():
    sizes = {f"o{i}": (i + 1) * 10 for i in range(20)}
    shards = assign_owners_to_shards(sizes, 4)
    assert sorted(o for s in shards for o in s) == sorted(sizes)
    loads = [sum(sizes[o] for o in s) for s in shards]
    assert max(loads) - min(loads) <= max(sizes.values())


def test_sharded_reconcile_matches_host_oracle():
    mesh = create_mesh()
    owner_batches = {
        f"owner{i}": _mk_messages(f"{i:016x}", 50 + 17 * i) for i in range(12)
    }
    existing = {o: {} for o in owner_batches}
    results, digest = reconcile_owner_batches(mesh, owner_batches, existing)

    expected_digest = 0
    for owner, msgs in owner_batches.items():
        xor_mask, upserts, deltas = results[owner]
        exp_xor, exp_upserts = plan_batch(msgs, {})
        assert xor_mask == exp_xor, owner
        # Upsert ORDER differs (host: cell-first-seen; device: batch
        # position of the winning message) but each upsert hits a
        # distinct cell, so order carries no semantics.
        assert set(upserts) == set(exp_upserts), owner
        # Per-owner deltas reproduce the sequential tree exactly.
        exp_deltas = {}
        from evolu_tpu.core.merkle import minutes_base3

        for i, m in enumerate(msgs):
            if exp_xor[i]:
                ts = timestamp_from_string(m.timestamp)
                k = minutes_base3(ts.millis)
                exp_deltas[k] = to_int32(exp_deltas.get(k, 0) ^ timestamp_to_hash(ts))
                expected_digest ^= timestamp_to_hash(ts) & 0xFFFFFFFF
        assert deltas == exp_deltas, owner
    assert digest == expected_digest


def test_sharded_reconcile_respects_existing_winners():
    mesh = create_mesh()
    msgs = _mk_messages("a" * 16, 10)
    # Existing winner newer than everything: no upserts for that cell.
    cells = {(m.table, m.row, m.column) for m in msgs}
    winner = "2099-01-01T00:00:00.000Z-0000-ffffffffffffffff"
    existing = {"o1": {c: winner for c in cells}}
    results, _ = reconcile_owner_batches(mesh, {"o1": msgs}, existing)
    xor_mask, upserts, _deltas = results["o1"]
    assert upserts == []
    assert xor_mask == [True] * len(msgs)  # hashes still enter the tree


def test_hot_owner_client_receive_end_to_end():
    """A single client IS one owner: a receive batch at/above
    hot_owner_min_batch routes through the cell-range-sharded kernel
    spanning the 8-device mesh, with SQLite end state and persisted
    clock byte-identical to the CPU-oracle client."""
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_runtime import TODO_SCHEMA, create_evolu

    from evolu_tpu.core.merkle import merkle_tree_to_string
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.storage.clock import read_clock
    from evolu_tpu.utils.config import Config

    base = 1_700_000_000_000
    messages = tuple(
        CrdtMessage(
            timestamp_to_string(Timestamp(base + i, i % 3, f"{(i % 5):016x}")),
            "todo", f"r{i % 97}", "title", f"v{i}",
        )
        for i in range(600)
    )
    hot = create_evolu(TODO_SCHEMA, config=Config(backend="tpu", hot_owner_min_batch=64))
    cpu = create_evolu(TODO_SCHEMA, config=Config(backend="cpu"),
                       mnemonic=hot.owner.mnemonic)
    # Pin the routing: the receive must actually go through the
    # cell-range-sharded kernel, not silently fall back.
    import evolu_tpu.parallel.hot_owner as hot_mod
    calls = []
    orig = hot_mod.reconcile_hot_owner
    hot_mod.reconcile_hot_owner = lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
    try:
        for c in (hot, cpu):
            c.receive(messages, "{}", None)
            c.worker.flush()
        assert calls, "hot-owner kernel was never invoked"
        dump_hot = hot.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
        dump_cpu = cpu.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
        assert len(dump_hot) == len(messages) and dump_hot == dump_cpu
        rows_hot = hot.db.exec('SELECT * FROM "todo" ORDER BY "id"')
        rows_cpu = cpu.db.exec('SELECT * FROM "todo" ORDER BY "id"')
        assert rows_hot == rows_cpu
        th = merkle_tree_to_string(read_clock(hot.db).merkle_tree)
        tc = merkle_tree_to_string(read_clock(cpu.db).merkle_tree)
        assert th == tc
    finally:
        hot_mod.reconcile_hot_owner = orig
        hot.dispose(), cpu.dispose()


def test_server_hot_owner_rows_split_across_shards():
    """An owner whose rows exceed an even shard's worth splits row-wise
    across the mesh (hashing needs no cell locality; XOR merges the
    per-shard per-minute partials exactly) — deltas and digest must
    equal the reference fold."""
    from evolu_tpu.core.merkle import minute_deltas_host
    from evolu_tpu.server.engine import owner_minute_deltas

    mesh = create_mesh()
    hot = [m.timestamp for m in _mk_messages("a" * 16, 5000)]
    small = [m.timestamp for m in _mk_messages("b" * 16, 40)]
    rows = {"hot": hot, "small": small}
    # Pin that the row-split path actually engages: the hot owner must
    # exceed an even shard's worth (engine splits when len > ceil(n/D)),
    # otherwise this test silently degrades to the unsplit path.
    even_share = -(-(len(hot) + len(small)) // mesh.devices.size)
    assert mesh.devices.size > 1 and len(hot) > even_share
    deltas, digest = owner_minute_deltas(mesh, rows)
    expect_digest = 0
    for o, ts_list in rows.items():
        expect, d = minute_deltas_host(ts_list)
        assert deltas[o] == expect, o
        expect_digest ^= d
    assert digest == expect_digest


def test_non_canonical_owner_quarantined_to_host_path():
    """An owner whose batch carries non-canonical hex case (uppercase
    node) is planned on the host with raw-string order and verbatim-case
    hashing; canonical owners stay on device; the combined digest covers
    both."""
    from evolu_tpu.core.merkle import minutes_base3

    mesh = create_mesh()
    clean = _mk_messages("c" * 16, 23)
    weird = [
        CrdtMessage("2022-07-03T18:41:40.000Z-0000-ABCDEF0123456789", "todo", "r", "title", "U"),
        CrdtMessage("2022-07-03T18:41:40.000Z-0000-abcdef0123456789", "todo", "r", "title", "L"),
        CrdtMessage("2022-07-03T18:41:41.000Z-0000-" + "b" * 16, "todo", "r2", "title", "x"),
    ]
    batches = {"clean": clean, "weird": weird}
    results, digest = reconcile_owner_batches(mesh, batches, {o: {} for o in batches})

    expected_digest = 0
    for owner, msgs in batches.items():
        xor_mask, upserts, deltas = results[owner]
        exp_xor, exp_upserts = plan_batch(msgs, {})
        assert xor_mask == exp_xor, owner
        assert set(upserts) == set(exp_upserts), owner
        exp_deltas = {}
        for i, m in enumerate(msgs):
            if exp_xor[i]:
                ts = timestamp_from_string(m.timestamp)
                k = minutes_base3(ts.millis)
                exp_deltas[k] = to_int32(exp_deltas.get(k, 0) ^ timestamp_to_hash(ts))
                expected_digest ^= timestamp_to_hash(ts) & 0xFFFFFFFF
        assert deltas == exp_deltas, owner
    assert digest == expected_digest


def test_single_owner_many_devices_and_empty():
    mesh = create_mesh()
    results, digest = reconcile_owner_batches(mesh, {}, {})
    assert results == {} and digest == 0
    msgs = _mk_messages("b" * 16, 3)
    results, _ = reconcile_owner_batches(mesh, {"only": msgs}, {"only": {}})
    assert len(results["only"][1]) == len(plan_batch(msgs, {})[1])


def test_high_contention_tiebreak_across_owners():
    """Config 4 analog: every owner's replicas write the same cells; the
    device tiebreak must match the string-order oracle exactly."""
    mesh = create_mesh()
    owner_batches = {}
    for o in range(4):
        msgs = []
        # 8 "replicas" stamp the same 5 rows at identical millis values:
        # order decided by (counter, node) alone.
        for r in range(8):
            node = f"{r:x}" * 16
            t = create_initial_timestamp(node[:16])
            for i in range(25):
                t = send_timestamp(t, 1_700_000_000_000)  # frozen clock
                msgs.append(
                    CrdtMessage(
                        timestamp_to_string(t), "todo", f"row{i % 5}", "title", f"{o}/{r}/{i}"
                    )
                )
        owner_batches[f"own{o}"] = msgs
    existing = {o: {} for o in owner_batches}
    results, _ = reconcile_owner_batches(mesh, owner_batches, existing)
    for o, msgs in owner_batches.items():
        exp_xor, exp_upserts = plan_batch(msgs, {})
        assert results[o][0] == exp_xor
        assert set(results[o][1]) == set(exp_upserts)


def test_tree_equivalence_after_delta_apply():
    """Applying the sharded deltas to an empty tree gives the identical
    tree to sequential inserts (whole-pipeline equivalence)."""
    from evolu_tpu.core.merkle import insert_into_merkle_tree

    mesh = create_mesh()
    msgs = _mk_messages("c" * 16, 200)
    results, _ = reconcile_owner_batches(mesh, {"o": msgs}, {"o": {}})
    xor_mask, _, deltas = results["o"]
    tree = apply_prefix_xors(create_initial_merkle_tree(), deltas)
    expected = create_initial_merkle_tree()
    for i, m in enumerate(msgs):
        if xor_mask[i]:
            expected = insert_into_merkle_tree(timestamp_from_string(m.timestamp), expected)
    assert tree == expected


# --- server batch reconcile engine ---


def _sync_req(user, node, messages=(), tree="{}"):
    from evolu_tpu.sync import protocol

    return protocol.SyncRequest(tuple(messages), user, node, tree)


def test_batch_reconciler_matches_sequential_store():
    """Engine end state == per-request store.sync end state (config 3)."""
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.relay import RelayStore
    from evolu_tpu.sync import protocol

    def enc(msgs):
        return tuple(protocol.EncryptedCrdtMessage(m.timestamp, b"ct-" + m.timestamp.encode()) for m in msgs)

    owners = {f"u{i:03d}": _mk_messages(f"{i:016x}", 30 + i * 5) for i in range(10)}
    requests = [
        _sync_req(o, msgs[0].timestamp[30:46], enc(msgs)) for o, msgs in owners.items()
    ]

    seq = RelayStore()
    for r in requests:
        seq.sync(r)

    batch_store = RelayStore()
    engine = BatchReconciler(batch_store, create_mesh())
    responses = engine.reconcile(requests)

    for o in owners:
        assert batch_store.get_merkle_tree(o) == seq.get_merkle_tree(o), o
    n_seq = seq.db.exec_sql_query('SELECT COUNT(*) AS n FROM "message"')[0]["n"]
    n_batch = batch_store.db.exec_sql_query('SELECT COUNT(*) AS n FROM "message"')[0]["n"]
    assert n_seq == n_batch
    # Each response excludes the requester's own messages; with one node
    # per owner and nothing else stored, responses are empty.
    assert all(r.messages == () for r in responses)


def test_batch_reconciler_idempotent_and_cross_device_fetch():
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.relay import RelayStore
    from evolu_tpu.sync import protocol

    store = RelayStore()
    engine = BatchReconciler(store, create_mesh())
    msgs = _mk_messages("d" * 16, 40)
    enc = tuple(protocol.EncryptedCrdtMessage(m.timestamp, b"x") for m in msgs)
    node = msgs[0].timestamp[30:46]
    r1 = _sync_req("u1", node, enc)
    engine.reconcile([r1])
    tree1 = store.get_merkle_tree("u1")
    engine.reconcile([r1])  # resend: no changes
    assert store.get_merkle_tree("u1") == tree1
    # A second device (different node, empty tree) gets the full history.
    r2 = _sync_req("u1", "e" * 16)
    (resp,) = engine.reconcile([r2])
    assert len(resp.messages) == len(msgs)


def test_reconcile_wire_byte_identical_to_object_respond():
    """`BatchReconciler.reconcile_wire` (r5: bytes-mode respond over
    `eh_get_messages_wire`) must be BYTE-identical to
    `encode_sync_response(reconcile(...)[i])` across push, cold pull,
    steady state, NUL-bearing ids, a sharded store, and the
    python-backend fallback — and a malformed stored timestamp must
    degrade that request to the object path, not wedge it."""
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.relay import RelayStore, ShardedRelayStore
    from evolu_tpu.sync import protocol

    def enc(msgs):
        return tuple(
            protocol.EncryptedCrdtMessage(m.timestamp, b"ct\x00-" + m.timestamp.encode())
            for m in msgs
        )

    owners = {f"w{i:03d}": _mk_messages(f"{i + 7:016x}", 25 + i * 3) for i in range(6)}
    owners["u\x00evil"] = _mk_messages("a" * 16, 10)  # NUL-bearing id
    push = [
        _sync_req(o, msgs[0].timestamp[30:46], enc(msgs)) for o, msgs in owners.items()
    ]
    cold = [_sync_req(o, "e" * 16) for o in owners]  # other-device pulls

    for mk in (lambda: RelayStore(), lambda: ShardedRelayStore(shards=3),
               lambda: RelayStore(backend="python")):
        obj_store, wire_store = mk(), mk()
        obj_eng = BatchReconciler(obj_store, create_mesh())
        wire_eng = BatchReconciler(wire_store, create_mesh())
        for batch in (push, cold, cold):  # cold twice = steady-state repeat
            want = [protocol.encode_sync_response(r) for r in obj_eng.reconcile(batch)]
            got = wire_eng.reconcile_wire(batch)
            assert got == want
        obj_eng.close(), wire_eng.close()
        obj_store.close(), wire_store.close()

    # Malformed stored width: rc 2 must degrade that request to the
    # object path (both engines serve the same bytes, no exception).
    from evolu_tpu.storage.native import native_available

    if native_available():
        obj_store, wire_store = RelayStore(), RelayStore()
        for s in (obj_store, wire_store):
            s.add_messages("u1", enc(owners["w000"]))
            s.db.run(
                'INSERT INTO "message" ("timestamp", "userId", "content") '
                "VALUES (?, ?, ?)",
                ("2099-01-01T00:00:00.000Z-00ff", "u1", b"bad"),
            )
        obj_eng = BatchReconciler(obj_store, create_mesh())
        wire_eng = BatchReconciler(wire_store, create_mesh())
        (want,) = obj_eng.reconcile([_sync_req("u1", "e" * 16)])
        (got,) = wire_eng.reconcile_wire([_sync_req("u1", "e" * 16)])
        assert got == protocol.encode_sync_response(want)
        obj_eng.close(), wire_eng.close()
        obj_store.close(), wire_store.close()


def test_hot_owner_cell_sharding_matches_single_device():
    """One hot owner's batch sharded by cell ranges over 8 devices must
    produce the single-device planner's exact masks, minute deltas, and
    digest (SURVEY.md §5 hot-owner strategy)."""
    import numpy as np

    from evolu_tpu.core.merkle import minutes_base3
    from evolu_tpu.core.murmur import to_int32
    from evolu_tpu.ops.encode import timestamp_hashes
    from evolu_tpu.ops.merge import plan_merge_core
    from evolu_tpu.ops.merkle_ops import merkle_minute_deltas, minute_deltas_to_dict
    from evolu_tpu.parallel.hot_owner import reconcile_hot_owner
    from evolu_tpu.parallel.mesh import create_mesh

    rng = np.random.default_rng(13)
    n = 3000
    base = 1_700_000_000_000
    cell_id = rng.integers(0, 400, n).astype(np.int32)
    millis = base + rng.integers(0, 10 * 60_000, n).astype(np.int64)
    counter = rng.integers(0, 16, n).astype(np.int32)
    node = rng.integers(1, 2**63, n).astype(np.uint64)
    k1 = (millis.astype(np.uint64) << np.uint64(16)) | counter.astype(np.uint64)
    k2 = node.copy()
    ex_k1 = np.zeros(n, np.uint64)
    ex_k2 = np.zeros(n, np.uint64)
    # Some cells have a stored winner mid-range.
    with_winner = cell_id % 3 == 0
    ex_k1[with_winner] = ((base + 5 * 60_000) << 16)
    ex_k2[with_winner] = 7

    mesh = create_mesh(8)
    got_xor, got_upsert, got_deltas, got_digest = reconcile_hot_owner(
        mesh, cell_id, k1, k2, ex_k1, ex_k2, millis, counter, node
    )

    import jax

    import jax.numpy as jnp

    with jax.enable_x64(True):
        args = tuple(jnp.asarray(a) for a in (cell_id, k1, k2, ex_k1, ex_k2))
        exp_xor, exp_upsert = (
            np.asarray(a) for a in plan_merge_core(*args, num_segments=n)
        )
        exp_deltas = minute_deltas_to_dict(
            *merkle_minute_deltas(millis, counter, node, exp_xor)
        )
        hashes = np.asarray(timestamp_hashes(millis, counter, node))
    np.testing.assert_array_equal(got_xor, exp_xor)
    np.testing.assert_array_equal(got_upsert, exp_upsert)
    assert got_deltas == exp_deltas
    exp_digest = 0
    for i in np.nonzero(exp_xor)[0]:
        exp_digest ^= int(hashes[i])
    assert got_digest == exp_digest


def test_multihost_helpers_single_process():
    """Single process hosts every shard; local_owners respects the
    actual LPT shard assignment. (jax.distributed.initialize itself
    must run before any backend exists, so it is not callable from
    inside the suite — the helpers are the testable surface.)"""
    import jax

    from evolu_tpu.parallel import multihost
    from evolu_tpu.parallel.mesh import assign_owners_to_shards, create_mesh

    mesh = create_mesh()
    assert not multihost.is_multihost()
    assert multihost.local_shard_indices(mesh) == list(range(mesh.devices.size))
    sizes = {f"o{i}": (i * 37) % 101 + 1 for i in range(10)}
    shards = assign_owners_to_shards(sizes, mesh.devices.size)
    assert sorted(multihost.local_owners(mesh, shards)) == sorted(sizes)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_dryrun_multichip_any_mesh_size(n):
    """The driver artifact must not be shape-specialized to n=8: the
    full sharded reconcile step compiles, runs, and digest-matches the
    host oracle at several mesh sizes (VERDICT r2 weak #7)."""
    import __graft_entry__ as graft

    graft.dryrun_multichip(n)


def test_reconcile_stream_matches_sequential_batches():
    """Pipelined streaming reconcile (device leg of batch k+1 in flight
    while batch k commits) must end byte-identical to sequential
    `reconcile` calls — across cross-batch duplicates, in-batch
    duplicates, owners spanning batches, a non-canonical-hex owner, and
    an all-duplicate replay batch (VERDICT r2 #1)."""
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.relay import ShardedRelayStore
    from evolu_tpu.sync import protocol

    def enc(msgs):
        return tuple(
            protocol.EncryptedCrdtMessage(m.timestamp, b"ct-" + m.timestamp.encode())
            for m in msgs
        )

    def req(owner, msgs, node="f" * 16):
        return _sync_req(owner, node, enc(msgs))

    a = _mk_messages("a" * 16, 40)
    b = _mk_messages("b" * 16, 35)
    c = _mk_messages("c" * 16, 30)
    weird = [
        CrdtMessage("2023-09-01T10:00:00.000Z-0000-ABCDEF0123456789",
                    "todo", "r", "title", "U"),
        CrdtMessage("2023-09-01T10:01:00.000Z-0001-ABCDEF0123456789",
                    "todo", "r", "title", "U2"),
    ]
    batches = [
        # batch 0: two owners, an in-batch duplicate for uA
        [req("uA", a[:20] + a[10:12]), req("uB", b[:15])],
        # batch 1: cross-batch duplicates (uA rows 10-19 again) + new
        # rows; owner uC and the non-canonical owner join
        [req("uA", a[10:30]), req("uC", c), req("uW", weird)],
        # batch 2: all-duplicate replay for uA and uW, fresh tail for uB
        [req("uA", a[:30]), req("uW", weird), req("uB", b[15:])],
    ]

    def dump(store):
        out = []
        for s in store.shards:
            out += s.db.exec_sql_query(
                'SELECT "timestamp","userId","content" FROM "message" '
                'ORDER BY "userId","timestamp"'
            )
            out += s.db.exec_sql_query(
                'SELECT "userId","merkleTree" FROM "merkleTree" ORDER BY "userId"'
            )
        return out

    seq_store = ShardedRelayStore(shards=4)
    seq_engine = BatchReconciler(seq_store, create_mesh())
    seq_responses = [seq_engine.reconcile(batch) for batch in batches]

    pipe_store = ShardedRelayStore(shards=4)
    pipe_engine = BatchReconciler(pipe_store, create_mesh())
    pipe_responses = pipe_engine.reconcile_stream(batches)

    assert dump(pipe_store) == dump(seq_store)
    for br_seq, br_pipe in zip(seq_responses, pipe_responses):
        assert [r.merkle_tree for r in br_seq] == [r.merkle_tree for r in br_pipe]
        assert [len(r.messages) for r in br_seq] == [len(r.messages) for r in br_pipe]


def test_compact_segment_overflow_falls_back_to_full_pull():
    """A batch whose distinct (owner, minute) pairs exceed the device
    compaction cap must detect the overflow and decode via the
    full-width pull, bit-identical to the host fold."""
    from evolu_tpu.core.merkle import minute_deltas_host
    from evolu_tpu.core.timestamp import Timestamp
    from evolu_tpu.server.engine import deltas_from_columns
    from evolu_tpu.ops.host_parse import parse_timestamp_strings

    base = 1_700_000_000_000
    owners = {}
    ts_all = []
    for o in range(64):
        # Every row its own minute: segments == rows, far above cap.
        msgs = [
            timestamp_to_string(Timestamp(base + (o * 97 + i) * 60_000, 0, "a" * 16))
            for i in range(64)
        ]
        owners[f"u{o:02d}"] = msgs
        ts_all.extend(msgs)
    all_m, all_c, all_n, case_ok = parse_timestamp_strings(ts_all, with_case=True)
    owner_index, pos = {}, 0
    for o, msgs in owners.items():
        owner_index[o] = np.arange(pos, pos + len(msgs))
        pos += len(msgs)

    deltas, digest = deltas_from_columns(
        create_mesh(), owner_index, all_m, all_c, all_n, case_ok, ts_all
    )
    expect_digest = 0
    for o, msgs in owners.items():
        exp, d = minute_deltas_host(msgs)
        assert deltas[o] == exp, o
        expect_digest ^= d
    assert digest == expect_digest


def test_reconcile_stream_bad_batch_lands_prior_batch():
    """A malformed batch k+1 raising in start_batch must not drop the
    already-dispatched batch k: the stream finishes it (matching
    sequential reconcile, which would commit k before raising), and
    the store remains serviceable afterwards."""
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.relay import ShardedRelayStore
    from evolu_tpu.sync import protocol

    def req(owner, msgs):
        return _sync_req(owner, "f" * 16, tuple(
            protocol.EncryptedCrdtMessage(m.timestamp, b"c") for m in msgs
        ))

    good = [req("uA", _mk_messages("a" * 16, 20))]
    bad = [protocol.SyncRequest(
        (protocol.EncryptedCrdtMessage("not-46-chars", b"c"),), "uB", "f" * 16, "{}"
    )]
    store = ShardedRelayStore(shards=2)
    engine = BatchReconciler(store, create_mesh())
    with pytest.raises(ValueError):
        engine.reconcile_stream([good, bad])
    stored = sum(
        s.db.exec('SELECT COUNT(*) FROM "message"')[0][0] for s in store.shards
    )
    assert stored == 20, "batch 1 must have committed despite batch 2 raising"
    # The engine keeps working after the error.
    engine.reconcile([req("uC", _mk_messages("c" * 16, 5))])
    stored = sum(
        s.db.exec('SELECT COUNT(*) FROM "message"')[0][0] for s in store.shards
    )
    assert stored == 25


def test_packed_owner_kernel_matches_wide_kernel():
    """The r5 packed-owner shard kernel (owner in the sort key's top
    bits, zero extra payloads) must produce BIT-identical outputs to
    the wide fallback on owner-consistent inputs — ties, stored-winner
    equal/greater flags, padding rows, multiple owners — and the
    host router must pick the wide kernel when ids exceed the packed
    bounds."""
    import jax
    import jax.numpy as jnp

    from evolu_tpu.ops.merge import _PAD_CELL
    from evolu_tpu.parallel.reconcile import (
        _shard_kernel,
        _shard_kernel_wide,
        shard_kernel_for,
    )

    from evolu_tpu.ops import shard_map
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(23)
    N = 1024  # 8 shards × 128
    mesh = create_mesh()

    def mapped(kern):
        spec = P("owners")
        return jax.jit(shard_map(
            kern, mesh=mesh, in_specs=(spec,) * 6,
            out_specs=(spec,) * 8 + (P(),), check_vma=False,
        ))

    with jax.enable_x64(True):
        packed = mapped(_shard_kernel)
        wide = mapped(_shard_kernel_wide)
        for trial in range(10):
            n = int(rng.integers(8, N))
            cells = int(rng.integers(1, n))
            cell = np.full(N, int(_PAD_CELL), np.int32)
            cell[:n] = rng.integers(0, cells, n)
            owner_of_cell = rng.integers(0, 16, cells)  # owner = f(cell)
            owner = np.zeros(N, np.int64)
            owner[:n] = owner_of_cell[cell[:n]]
            k1 = np.zeros(N, np.uint64); k2 = np.zeros(N, np.uint64)
            k1[:n] = rng.integers(1, 9, n); k2[:n] = rng.integers(0, 5, n)
            ex1 = np.zeros(N, np.uint64); ex2 = np.zeros(N, np.uint64)
            ex1_c = rng.integers(0, 9, cells).astype(np.uint64)
            ex2_c = rng.integers(0, 5, cells).astype(np.uint64)
            ex1[:n] = ex1_c[cell[:n]]; ex2[:n] = ex2_c[cell[:n]]
            args = tuple(map(jnp.asarray, (cell, k1, k2, ex1, ex2, owner)))
            a = packed(*args)
            b = wide(*args)
            # Sort orders differ (owner-major vs cell-major): compare
            # the masks in BATCH order via the shard-local permutation.
            from evolu_tpu.ops.merge import unpermute_masks

            block = N // mesh.devices.size
            xa, ua = unpermute_masks(
                np.asarray(a[0]), np.asarray(a[1]), np.asarray(a[2]),
                block_size=block,
            )
            xb, ub = unpermute_masks(
                np.asarray(b[0]), np.asarray(b[1]), np.asarray(b[2]),
                block_size=block,
            )
            assert np.array_equal(xa, xb), (trial, "xor")
            assert np.array_equal(ua, ub), (trial, "upsert")
            assert int(a[8]) == int(b[8]), (trial, "digest")
            # The (owner, minute) Merkle feed too — sorted orders
            # differ, so compare the order-insensitive decode.
            from evolu_tpu.ops.merkle_ops import decode_owner_minute_deltas

            da = decode_owner_minute_deltas(*(np.asarray(o) for o in a[3:8]))
            db_ = decode_owner_minute_deltas(*(np.asarray(o) for o in b[3:8]))
            assert da == db_, (trial, "minute deltas")

    # Router: in-bounds → packed; oversized cell ids or owners → wide
    # (plan path pinned to "sort" — the scatter route has its own
    # router pins in tests/test_scatter_merge.py).
    from evolu_tpu.ops.scatter_merge import set_plan_path

    set_plan_path("sort")
    try:
        small = {"cell_id": np.array([1, int(_PAD_CELL)], np.int32),
                 "owner_ix": np.array([3, 0], np.int64)}
        assert shard_kernel_for(small) is _shard_kernel
        big_cell = {"cell_id": np.array([1 << 25], np.int32),
                    "owner_ix": np.array([0], np.int64)}
        assert shard_kernel_for(big_cell) is _shard_kernel_wide
        big_owner = {"cell_id": np.array([1], np.int32),
                     "owner_ix": np.array([4095], np.int64)}
        assert shard_kernel_for(big_owner) is _shard_kernel_wide
    finally:
        set_plan_path("auto")


def test_run_batch_wire_on_generic_store_without_db_handle():
    """A store exposing only the RelayStore METHOD surface (no `.db`
    SQL handle at all) must route through the object-respond fallback
    instead of raising AttributeError (ADVICE r5) — byte-identical to
    a real RelayStore served the same batch."""
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.relay import RelayStore
    from evolu_tpu.sync import protocol

    class GenericStore:
        """Method-only facade over a private RelayStore."""

        def __init__(self):
            self._inner = RelayStore()

        def add_messages(self, user_id, messages):
            return self._inner.add_messages(user_id, messages)

        def get_messages(self, user_id, node_id, server_tree, client_tree):
            return self._inner.get_messages(user_id, node_id, server_tree, client_tree)

        def get_merkle_tree(self, user_id):
            return self._inner.get_merkle_tree(user_id)

        def close(self):
            self._inner.close()

    def enc(msgs):
        return tuple(
            protocol.EncryptedCrdtMessage(m.timestamp, b"ct-" + m.timestamp.encode())
            for m in msgs
        )

    owners = {f"g{i}": _mk_messages(f"{i + 3:016x}", 15 + i) for i in range(4)}
    push = [
        _sync_req(o, msgs[0].timestamp[30:46], enc(msgs)) for o, msgs in owners.items()
    ]
    cold = [_sync_req(o, "e" * 16) for o in owners]

    ref_store, gen_store = RelayStore(), GenericStore()
    ref_eng = BatchReconciler(ref_store, create_mesh())
    gen_eng = BatchReconciler(gen_store, create_mesh())
    try:
        for batch in (push, cold):
            want = ref_eng.run_batch_wire(batch)
            got = gen_eng.run_batch_wire(batch)
            assert got == want
    finally:
        ref_eng.close(), gen_eng.close()
        ref_store.close(), gen_store.close()


def test_delta_compact_transfer_matches_full_key_kernel(monkeypatch):
    """The 16 B/row delta-encoded compact upload (VERDICT #9) must
    produce identical deltas + digest to the 20 B/row packed-HLC-key
    kernel, and batches outside its admission bounds (millis span
    ≥ 2^32 ms) must silently keep the full-key kernel — same results
    either way."""
    from evolu_tpu.core.merkle import minute_deltas_host
    from evolu_tpu.core.timestamp import Timestamp
    from evolu_tpu.server.engine import deltas_from_columns
    from evolu_tpu.ops.host_parse import parse_timestamp_strings

    base = 1_700_000_000_000
    mesh = create_mesh()

    def run(spread):
        owners, ts_all = {}, []
        for o in range(5):
            msgs = [
                timestamp_to_string(
                    Timestamp(base + o * 60_000 + i * spread, i % 3, f"{o + 1:016x}")
                )
                for i in range(40)
            ]
            owners[f"u{o}"] = msgs
            ts_all.extend(msgs)
        all_m, all_c, all_n, case_ok = parse_timestamp_strings(ts_all, with_case=True)
        owner_index, pos = {}, 0
        for o, msgs in owners.items():
            owner_index[o] = np.arange(pos, pos + len(msgs))
            pos += len(msgs)
        out = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("EVOLU_COMPACT_DELTA", flag)
            out[flag] = deltas_from_columns(
                mesh, owner_index, all_m, all_c, all_n, case_ok, ts_all
            )
        monkeypatch.delenv("EVOLU_COMPACT_DELTA")
        # Host oracle cross-check, not just self-consistency.
        expect_digest = 0
        for o, msgs in owners.items():
            exp, d = minute_deltas_host(msgs)
            assert out["1"][0][o] == exp, o
            expect_digest ^= d
        assert out["1"] == out["0"]
        assert out["1"][1] == expect_digest

    run(spread=977)            # in-bounds: the delta kernel serves it
    run(spread=120_000_000_00)  # 1.2e10 ms × 40 rows ≫ 2^32: full-key fallback
