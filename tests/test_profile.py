"""GET /profile — live relay profiling (ISSUE 16): a token-gated
capture of real traffic as a loadable chrome/perfetto trace (host span
lanes always; the jax.profiler device lane only when jax is already
loaded), single-flight 429, ms validation/clamping, and the engine
integration proof that driven device traffic populates the anatomy
plane's runtime stages on GET /stats."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import anatomy
from evolu_tpu.server import relay as relay_mod
from evolu_tpu.server.relay import RelayServer, RelayStore
from evolu_tpu.sync import protocol
from evolu_tpu.utils.log import logger

BASE = 1_700_000_000_000


@pytest.fixture(autouse=True)
def _clean_slate():
    logger.clear()
    yield
    logger.configure(False)
    logger.clear()


def _get(url, headers=None):
    # Generous timeout: a /profile capture pays jax.profiler start/stop
    # overhead ON TOP of the requested window, and in a process loaded
    # with hundreds of prior compilations that teardown alone can take
    # tens of seconds (observed >30s in the full suite).
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=300) as r:
        return r.read()


def _post(url, req):
    body = protocol.encode_sync_request(req)
    r = urllib.request.urlopen(
        urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/octet-stream"}
        ),
        timeout=30,
    )
    return r.read()


def _sync_req(user, node, n_msgs, start=0):
    msgs = tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (start + i) * 1000, 0, node)),
            b"ct-%d" % (start + i),
        )
        for i in range(n_msgs)
    )
    return protocol.SyncRequest(msgs, user, node, "{}")


def test_profile_captures_live_traffic():
    """The operator runbook path: GET /profile?ms=N against a relay
    serving real traffic answers one loadable chrome-trace JSON whose
    events include the live sync spans from inside the window."""
    server = RelayServer(RelayStore()).start()
    stop = threading.Event()

    def traffic():
        i = 0
        while not stop.is_set():
            _post(server.url, _sync_req("prof-user", "c" * 16, n_msgs=2,
                                        start=i * 10))
            i += 1

    t = threading.Thread(target=traffic, daemon=True)
    t.start()
    try:
        body = _get(server.url + "/profile?ms=300")
        doc = json.loads(body)
        assert doc["displayTimeUnit"] == "ms"
        events = doc["traceEvents"]
        assert events, "profile of a live relay captured no events"
        names = {e.get("name", "") for e in events}
        assert any("relay.sync" in n for n in names), sorted(names)[:20]
        # Every event is a well-formed chrome event (perfetto loads by
        # these fields); complete events carry µs ts/dur.
        for e in events:
            assert "ph" in e and "pid" in e
            if e.get("ph") == "X":
                assert isinstance(e["ts"], (int, float))
                assert isinstance(e["dur"], (int, float))
        meta = doc["metadata"]
        assert meta["requested_ms"] == 300.0
        assert meta["wall_ms"] >= 300.0
        assert isinstance(meta["jax_profiler"], bool)
    finally:
        stop.set()
        t.join(timeout=10)
        server.stop()


def test_profile_ms_validation_and_clamp():
    server = RelayServer(RelayStore()).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server.url + "/profile?ms=abc")
        assert e.value.code == 400
        # Sub-minimum ms clamps to 10ms, not an error. The clamp is
        # asserted on the echoed request window (wall time additionally
        # carries profiler start/stop overhead, which is load-dependent).
        doc = json.loads(_get(server.url + "/profile?ms=0"))
        assert doc["metadata"]["requested_ms"] == 10.0
        assert doc["metadata"]["wall_ms"] >= 10.0
    finally:
        server.stop()


def test_profile_token_gate(monkeypatch):
    server = RelayServer(RelayStore()).start()
    try:
        monkeypatch.setenv("EVOLU_OBS_TOKEN", "s3cret")
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server.url + "/profile?ms=10")
        assert e.value.code == 403
        doc = json.loads(_get(server.url + "/profile?ms=10",
                              {"X-Evolu-Obs-Token": "s3cret"}))
        assert "traceEvents" in doc
    finally:
        server.stop()


def test_profile_single_flight_answers_429():
    server = RelayServer(RelayStore()).start()
    try:
        assert relay_mod._PROFILE_LOCK.acquire(blocking=False)
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + "/profile?ms=10")
            assert e.value.code == 429
        finally:
            relay_mod._PROFILE_LOCK.release()
        json.loads(_get(server.url + "/profile?ms=10"))  # released: serves
    finally:
        server.stop()


def test_stats_stages_section_reports_runtime_anatomy():
    """Engine-wiring integration proof: one real device batch through
    BatchReconciler populates device_dispatch / host_apply / pull_wave
    in the anatomy plane, and GET /stats surfaces them with shares."""
    from evolu_tpu.parallel import create_mesh
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.relay import ShardedRelayStore

    store = ShardedRelayStore(shards=2)
    engine = BatchReconciler(store, create_mesh())
    msgs = tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + i * 1000, 0, "d" * 16)),
            b"ct-%d" % i,
        )
        for i in range(24)
    )
    # reconcile_stream drives the start_batch/finish_batch seams where
    # the device_dispatch/host_apply stage records live.
    engine.reconcile_stream(
        [[protocol.SyncRequest(msgs, "stage-user", "d" * 16, "{}")]])

    payload = anatomy.stages_payload()
    stages = payload["stages"]
    for name in ("device_dispatch", "host_apply", "pull_wave"):
        assert stages.get(name, {}).get("count", 0) > 0, (name, stages.keys())
    shares = [stages[s]["share"] for s in anatomy.RUNTIME_SHARE_STAGES]
    assert all(s is not None for s in shares)
    assert sum(shares) == pytest.approx(1.0)
    # The same section rides GET /stats.
    server = RelayServer(RelayStore()).start()
    try:
        stats = json.loads(_get(server.url + "/stats"))
        assert stats["stages"]["registry_digest"] == anatomy.registry_digest()
        assert "device_dispatch" in stats["stages"]["stages"]
    finally:
        server.stop()
