"""Push subscriptions (evolu_tpu/server/push.py + the client leg in
sync/client.py — ISSUE 13).

Semantic ground truth — wakeup == changed-set oracle at the relay's
E2EE granularity: a parked subscription (owner O, node N) must wake
for EXACTLY the batches that make rows visible for O authored by a
node other than N ("no wakeup missed"), and never more often than
once per such batch ("spurious wakeups bounded"). Anti-entropy stays
the correctness mechanism (the sync round a wake triggers is the same
round a timer would fire), so every lane here is about latency
precision, with the conservative over-approximations explicitly
pinned: unknown authors wake everyone, an out-ringed cursor wakes
conservatively, a snapshot install wakes everything.
"""

import json
import threading
import time
import urllib.request

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server import push as push_mod
from evolu_tpu.server.push import HubFull, PushHub, parse_poll_query
from evolu_tpu.server.relay import RelayServer, RelayStore
from evolu_tpu.sync import protocol
from evolu_tpu.sync.client import PushSubscriber
from evolu_tpu.utils.config import Config, FleetConfig

BASE = 1_730_000_000_000
NODE_A = "a" * 16
NODE_B = "b" * 16
SUB = "5" * 16  # the subscriber's node


def _ts(node: str, i: int) -> str:
    return timestamp_to_string(Timestamp(BASE + i * 1000, 0, node))


def _msgs(node: str, start: int, n: int):
    return tuple(
        protocol.EncryptedCrdtMessage(_ts(node, start + i), b"c%d" % (start + i))
        for i in range(n)
    )


def _sync_body(owner, node, messages, tree="{}"):
    return protocol.encode_sync_request(
        protocol.SyncRequest(messages, owner, node, tree))


# -- hub unit surface --


def test_hub_wake_and_own_write_exclusion():
    hub = PushHub()
    results = {}

    def poll(name, node, cursor, timeout):
        results[name] = json.loads(hub.poll_blocking("o", node, cursor, timeout))

    t = threading.Thread(target=poll, args=("sub", SUB, 0, 5.0))
    t.start()
    time.sleep(0.1)
    # Self-authored batch: parked subscriber must NOT wake.
    assert hub.notify("o", [_ts(SUB, 0)]) == 0
    # Foreign batch wakes it.
    assert hub.notify("o", [_ts(NODE_A, 1)]) == 1
    t.join(timeout=5)
    assert results["sub"] == {"wake": True, "cursor": 2}
    # Resume from that cursor: nothing new → parks → times out false.
    body = json.loads(hub.poll_blocking("o", SUB, 2, 0.1))
    assert body == {"wake": False, "cursor": 2}
    # A cursor behind events that were ALL self-authored: no wake, but
    # the returned cursor advances past them.
    hub.notify("o", [_ts(SUB, 2)])
    body = json.loads(hub.poll_blocking("o", SUB, 2, 0.1))
    assert body == {"wake": False, "cursor": 3}
    # Mixed batch (self + foreign) wakes: any foreign row qualifies.
    t2 = threading.Thread(target=poll, args=("sub2", SUB, 3, 5.0))
    t2.start()
    time.sleep(0.05)
    hub.notify("o", [_ts(SUB, 3), _ts(NODE_B, 4)])
    t2.join(timeout=5)
    assert results["sub2"]["wake"] is True


def test_hub_immediate_answers_and_stale_cursor():
    hub = PushHub()
    hub.notify("o", [_ts(NODE_A, 0)])
    # Events already past the cursor: answered without parking.
    assert json.loads(hub.poll_blocking("o", SUB, 0, 5.0)) == {
        "wake": True, "cursor": 1}
    # Unknown-author batch wakes even the author-matching node.
    hub.notify("o", None)
    assert json.loads(hub.poll_blocking("o", NODE_A, 1, 5.0))["wake"] is True
    # A cursor the bounded ring outgrew: conservative wake, never a miss.
    for i in range(push_mod.EVENT_RING + 10):
        hub.notify("o", [_ts(SUB, i)])  # all self-authored!
    body = json.loads(hub.poll_blocking("o", SUB, 1, 5.0))
    assert body["wake"] is True  # can't prove self-only → wake


def test_hub_capacity_and_close():
    hub = PushHub(max_subscriptions=2)
    t1 = threading.Thread(
        target=lambda: hub.poll_blocking("o1", SUB, 0, 5.0))
    t2 = threading.Thread(
        target=lambda: hub.poll_blocking("o2", SUB, 0, 5.0))
    t1.start(), t2.start()
    deadline = time.monotonic() + 5
    while hub.stats_payload()["subscriptions"] < 2:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    with pytest.raises(HubFull):
        hub.poll_blocking("o3", SUB, 0, 5.0)
    hub.close()  # resolves both parks with wake=false
    t1.join(timeout=5), t2.join(timeout=5)
    assert not t1.is_alive() and not t2.is_alive()
    assert hub.stats_payload()["subscriptions"] == 0


def test_parse_poll_query_contract():
    # 5th element (ISSUE 18): optional scope-lane tags, None = unscoped.
    assert parse_poll_query(
        f"owner=o&node={SUB}&cursor=3") == ("o", SUB, 3, None, None)
    assert parse_poll_query(
        f"owner=o&node={SUB}&cursor=0&timeout=2.5") == ("o", SUB, 0, 2.5, None)
    for bad in ("", "owner=o", f"owner=o&node=XYZ&cursor=0",
                f"owner=o&node={SUB}&cursor=x",
                f"owner=o&node={SUB}&cursor=0&timeout=nan",
                f"owner=o&node={SUB}&cursor=0&timeout=-1",
                f"owner=o&node={'A' * 16}&cursor=0"):
        with pytest.raises(ValueError):
            parse_poll_query(bad)


# -- wakeup == changed-set oracle, through a live relay --


@pytest.mark.parametrize("tier", ["threaded", "eventloop"])
def test_wakeups_match_changed_set_oracle(tier):
    """A seeded mutation schedule against a live relay: the subscriber
    (long-polling continuously) must wake at least once after every
    foreign-authored batch (no miss), never for self-only batches, and
    no more than once per qualifying batch overall (spurious bound)."""
    import random

    rng = random.Random(20260804)
    srv = RelayServer(RelayStore(), connection_tier=tier).start()
    wakes = []
    stop = threading.Event()

    def subscriber():
        cursor = 0
        while not stop.is_set():
            url = (f"{srv.url}/push/poll?owner=ow&node={SUB}"
                   f"&cursor={cursor}&timeout=1.0")
            try:
                with urllib.request.urlopen(url, timeout=10) as r:
                    body = json.loads(r.read())
            except Exception:  # noqa: BLE001 - server stopping
                return
            cursor = body["cursor"]
            if body["wake"]:
                wakes.append(time.monotonic())

    th = threading.Thread(target=subscriber)
    th.start()
    try:
        time.sleep(0.2)  # let the first poll park
        foreign_batches = 0
        i = 0
        for _step in range(12):
            author = rng.choice([SUB, NODE_A, NODE_B])
            n = rng.randint(1, 4)
            body = _sync_body("ow", author, _msgs(author, i, n))
            i += n
            before = len(wakes)
            with urllib.request.urlopen(
                    urllib.request.Request(srv.url + "/", data=body),
                    timeout=10) as r:
                assert r.status == 200
            if author != SUB:
                foreign_batches += 1
                # No wakeup missed: the parked subscriber (or its next
                # poll via cursor) must observe this batch.
                deadline = time.monotonic() + 5
                while len(wakes) == before:
                    assert time.monotonic() < deadline, \
                        f"missed wakeup for foreign batch at step {_step}"
                    time.sleep(0.01)
            else:
                # Self-only batch: give a wrongful wake a moment to
                # appear, then assert it didn't.
                time.sleep(0.15)
                assert len(wakes) == before, \
                    "subscriber woke for its own writes"
        # Spurious bound: at most one wake per qualifying batch.
        assert len(wakes) <= foreign_batches
        assert foreign_batches > 0
    finally:
        stop.set()
        srv.stop()
        th.join(timeout=5)


# -- fleet interplay: the subscription follows placement --


@pytest.mark.parametrize("forward", [False, True])
def test_push_poll_follows_fleet_placement(forward):
    """A poll landing on a non-placed relay answers 307 to the placed
    one — in forward mode too (a proxied long-poll would pin the hop).
    A mutation arriving at the placed relay (directly or via
    forward/redirect routing) wakes the parked subscription there."""
    a = RelayServer(RelayStore(), connection_tier="eventloop")
    b = RelayServer(RelayStore(), connection_tier="eventloop")
    cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                      forward=forward)
    a.enable_fleet(cfg)
    b.enable_fleet(cfg)
    a.start(), b.start()
    try:
        ring = a.fleet.ring
        owner = next(f"own-{i}" for i in range(1000)
                     if ring.placement(f"own-{i}")[0] == b.url)
        wrong, right = a, b
        # Poll at the WRONG relay: 307 naming the placed one.
        import urllib.error

        class NoRedirect(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **k):
                return None

        opener = urllib.request.build_opener(NoRedirect)
        path = f"/push/poll?owner={owner}&node={SUB}&cursor=0&timeout=5"
        with pytest.raises(urllib.error.HTTPError) as ei:
            opener.open(wrong.url + path, timeout=10)
        assert ei.value.code == 307
        assert ei.value.headers["Location"] == right.url + path
        # Park at the RIGHT relay; write through the WRONG one (the
        # fleet routes it — forward or redirect) and assert the wake.
        result = {}

        def poll():
            with urllib.request.urlopen(right.url + path, timeout=15) as r:
                result["body"] = json.loads(r.read())

        th = threading.Thread(target=poll)
        th.start()
        deadline = time.monotonic() + 5
        while right.push_hub.stats_payload()["subscriptions"] != 1:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        body = _sync_body(owner, NODE_A, _msgs(NODE_A, 0, 2))
        if forward:
            with urllib.request.urlopen(
                    urllib.request.Request(wrong.url + "/", data=body),
                    timeout=10) as r:
                assert r.status == 200
        else:
            with pytest.raises(urllib.error.HTTPError) as ei:
                opener.open(urllib.request.Request(
                    wrong.url + "/", data=body), timeout=10)
            assert ei.value.code == 307
            with urllib.request.urlopen(
                    urllib.request.Request(right.url + "/", data=body),
                    timeout=10) as r:
                assert r.status == 200
        th.join(timeout=10)
        assert result["body"]["wake"] is True
    finally:
        a.stop(), b.stop()


# -- client subscriber --


def test_client_subscriber_wakes_and_resumes():
    srv = RelayServer(RelayStore(), connection_tier="eventloop").start()
    woken = threading.Event()
    try:
        sub = PushSubscriber(Config(sync_url=srv.url), woken.set,
                             poll_timeout_s=2.0)
        sub.ensure("ow", SUB, srv.url)
        time.sleep(0.2)
        with urllib.request.urlopen(urllib.request.Request(
                srv.url + "/", data=_sync_body("ow", NODE_A,
                                               _msgs(NODE_A, 0, 1))),
                timeout=10) as r:
            assert r.status == 200
        assert woken.wait(5), "push wake never fired"
        assert sub.cursor >= 1
        # Resume: a second foreign write wakes again from the new cursor.
        woken.clear()
        with urllib.request.urlopen(urllib.request.Request(
                srv.url + "/", data=_sync_body("ow", NODE_A,
                                               _msgs(NODE_A, 10, 1))),
                timeout=10) as r:
            assert r.status == 200
        assert woken.wait(5)
        sub.stop()
    finally:
        srv.stop()


def test_client_subscriber_survives_outage_with_backoff():
    """Relay unreachable: the loop backs off (never spins), then
    resumes — with its cursor — once polls succeed again."""
    calls = []
    gate = {"fail": True}

    def fake_get(url, timeout):
        calls.append((time.monotonic(), url))
        if gate["fail"]:
            raise OSError("refused")
        return json.dumps({"wake": True, "cursor": 7}).encode()

    woken = threading.Event()
    sub = PushSubscriber(Config(sync_url="http://127.0.0.1:9"),
                         woken.set, http_get=fake_get, poll_timeout_s=0.2)
    sub.ensure("ow", SUB, "http://127.0.0.1:9")
    time.sleep(1.0)
    n_during_outage = len(calls)
    assert 1 <= n_during_outage <= 12, \
        f"{n_during_outage} polls in 1s of outage — backoff missing"
    gate["fail"] = False
    assert woken.wait(10)
    assert sub.cursor == 7
    assert "cursor=0" in calls[0][1]
    sub.stop()


def test_client_subscriber_follows_307():
    import urllib.error
    from email.message import Message

    target = {"hits": []}

    def fake_get(url, timeout):
        target["hits"].append(url)
        if url.startswith("http://wrong"):
            hdrs = Message()
            hdrs["Location"] = "http://right:1/push/poll?x=1"
            raise urllib.error.HTTPError(url, 307, "moved", hdrs, None)
        return json.dumps({"wake": False, "cursor": 0}).encode()

    sub = PushSubscriber(Config(sync_url="http://wrong:1"),
                         lambda: None, http_get=fake_get,
                         poll_timeout_s=0.1)
    sub.ensure("ow", SUB, "http://wrong:1")
    deadline = time.monotonic() + 5
    while not any(u.startswith("http://right:1/push/poll")
                  for u in target["hits"]):
        assert time.monotonic() < deadline, target["hits"]
        time.sleep(0.02)
    sub.stop()


def test_connect_wires_push_subscribe():
    """Config.push_subscribe: the transport binds the subscriber from
    its first successful round, and a foreign mutation then reaches
    the client without any explicit sync — the full client loop."""
    from evolu_tpu.api.query import table
    from evolu_tpu.runtime.client import create_evolu
    from evolu_tpu.sync.client import connect

    schema = {"todo": ("title", "isCompleted", "createdAt", "updatedAt",
                       "isDeleted", "createdBy")}
    srv = RelayServer(RelayStore(), connection_tier="eventloop").start()
    cfg = Config(sync_url=srv.url, push_subscribe=True)
    a = create_evolu(schema, config=cfg)
    b = create_evolu(schema, config=cfg, mnemonic=a.owner.mnemonic)
    ta, tb = connect(a), connect(b)
    try:
        assert tb.push_subscriber is not None
        a.sync(refresh_queries=False)
        b.sync(refresh_queries=False)
        a.worker.flush(); ta.flush(); b.worker.flush(); tb.flush()
        q = table("todo").select("title").serialize()
        a.create("todo", {"title": "pushed", "isCompleted": False})
        a.worker.flush(); ta.flush()
        deadline = time.monotonic() + 10
        rows = []
        while time.monotonic() < deadline:
            rows = b.query_once(q)
            if rows:
                break
            time.sleep(0.05)
        assert rows == [{"title": "pushed"}]
        assert tb.push_subscriber.wakes >= 1
        # Own-write exclusion end to end: A's subscriber was not woken
        # by A's own mutation (B's ack rows may wake it later, so pin
        # only the pre-convergence window semantics via the counter
        # BEFORE b writes anything).
        assert ta.push_subscriber.wakes == 0
    finally:
        a.dispose(); b.dispose(); srv.stop()


# -- review-fix regressions --


def test_cursor_from_newer_epoch_wakes_conservatively():
    """A cursor AHEAD of the channel (minted by another hub epoch —
    relay restart, retarget) must wake conservatively, never park as
    'seen everything' (the missed-wakeup hole)."""
    hub = PushHub()
    hub.notify("o", [_ts(NODE_A, 0)])  # seq = 1
    body = json.loads(hub.poll_blocking("o", SUB, 999, 5.0))
    assert body["wake"] is True and body["cursor"] == 1
    # And with no channel at all, a stale-epoch cursor parks safely:
    # the first foreign notify wakes by author, cursor-independent.
    t = threading.Thread(
        target=lambda: hub.poll_blocking("fresh", SUB, 999, 5.0))
    t.start()
    time.sleep(0.1)
    assert hub.notify("fresh", [_ts(NODE_A, 0)]) == 1
    t.join(timeout=5)


def test_client_adopts_smaller_cursor_after_relay_restart():
    """The subscriber must ADOPT the relay's cursor (per-hub epochs),
    not max() it — else post-restart polls carry the dead epoch's
    cursor forever."""
    seen = []

    def fake_get(url, timeout):
        seen.append(url)
        if len(seen) == 1:
            return json.dumps({"wake": True, "cursor": 500}).encode()
        return json.dumps({"wake": False, "cursor": 2}).encode()

    sub = PushSubscriber(Config(sync_url="http://x:1"), lambda: None,
                         http_get=fake_get, poll_timeout_s=0.1)
    sub.ensure("ow", SUB, "http://x:1")
    deadline = time.monotonic() + 5
    while len(seen) < 3:
        assert time.monotonic() < deadline
        time.sleep(0.02)
    sub.stop()
    assert sub.cursor == 2
    assert any("cursor=2" in u for u in seen[2:])


def test_client_307_pingpong_is_bounded():
    """Two relays 307-ing at each other (mid-rebalance ring
    disagreement) must not spin a hot request loop: the second
    consecutive 307 drops the route and backs off."""
    import urllib.error
    from email.message import Message

    calls = []

    def fake_get(url, timeout):
        calls.append(time.monotonic())
        hdrs = Message()
        other = "http://b:1" if url.startswith("http://a:1") else "http://a:1"
        hdrs["Location"] = other + "/push/poll?x=1"
        raise urllib.error.HTTPError(url, 307, "moved", hdrs, None)

    sub = PushSubscriber(Config(sync_url="http://a:1"), lambda: None,
                         http_get=fake_get, poll_timeout_s=0.1)
    sub.ensure("ow", SUB, "http://a:1")
    time.sleep(1.0)
    sub.stop()
    assert len(calls) <= 30, \
        f"{len(calls)} requests in 1s of 307 ping-pong — no backoff"


def test_notify_all_reaches_between_polls_subscribers():
    """A snapshot install (notify_all) must be observable by a
    subscriber that was BETWEEN polls at the time — for owners with an
    existing channel (bumped) AND for owners the hub never saw a
    notify for (conservative first-poll wake after any install)."""
    hub = PushHub()
    # Known owner: subscriber synced before (channel exists), is
    # between polls when the install lands.
    hub.notify("known", [_ts(SUB, 0)])
    cursor = json.loads(hub.poll_blocking("known", SUB, 0, 0.05))["cursor"]
    hub.notify_all()
    body = json.loads(hub.poll_blocking("known", SUB, cursor, 5.0))
    assert body["wake"] is True, "install missed for a known owner"
    # Never-seen owner: no channel at all at install time.
    body = json.loads(hub.poll_blocking("unseen", SUB, 0, 5.0))
    assert body["wake"] is True, "install missed for a never-seen owner"
    # The conservative wake self-terminates: next poll parks normally.
    body2 = json.loads(hub.poll_blocking("unseen", SUB, body["cursor"], 0.05))
    assert body2["wake"] is False


def test_expiry_heap_handles_many_staggered_parks():
    """Staggered event-tier parks expire individually (lazy-deletion
    heap) and a wakeup between expiries is never blocked or lost."""
    hub = PushHub()
    resolved = []
    hub.on_wake = lambda token, body: resolved.append(
        (token, json.loads(body)))
    for i in range(50):
        kind, _ = hub.park(f"o{i}", SUB, 0, 0.05 + i * 0.01, token=f"t{i}")
        assert kind == "parked"
    # Wake one mid-schedule before its expiry.
    hub.notify("o40", [_ts(NODE_A, 0)])
    deadline = time.monotonic() + 10
    while len(resolved) < 50:
        hub.expire_due()
        assert time.monotonic() < deadline, len(resolved)
        time.sleep(0.01)
    woken = {t: b for t, b in resolved}
    assert woken["t40"]["wake"] is True
    assert sum(1 for b in woken.values() if not b["wake"]) == 49
    assert hub.stats_payload()["subscriptions"] == 0
