"""Changed-set-gated incremental query invalidation (ISSUE 9).

Layers under test:
- `storage/deps.py`: table extraction from SQLite's compiled program
  (EXPLAIN opcode walk) and the sound static `"id" = ?` row filters;
- `storage/changes.py`: the ChangedSet contract (over-approximation,
  "don't know" escalation, row-set cap);
- `runtime/worker.py::_query` gating: table-disjoint / row-disjoint /
  clean skips, conservative fallbacks, LRU cache bounding with
  root-replace self-healing, the `Query(full=True)` bypass, and —
  the acceptance criterion — BYTE-IDENTICAL output streams between a
  gated worker and the re-run-everything oracle over schedules that
  cross every apply path (object, packed, host-fallback, typed CRDT,
  rollback, chunked receive).

The dual-worker harness drives `handle()` synchronously with a fixed
mnemonic and deterministic clocks, so two workers fed the same command
schedule must emit equal outputs regardless of gating.
"""

import itertools

import pytest

from evolu_tpu.core.merkle import create_initial_merkle_tree, merkle_tree_to_string
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage, NewCrdtMessage, TableDefinition
from evolu_tpu.obs import metrics
from evolu_tpu.runtime import messages as msg
from evolu_tpu.runtime.jsonpatch import apply_patch
from evolu_tpu.runtime.worker import DbWorker
from evolu_tpu.storage.changes import ROW_SET_CAP, ChangedSet
from evolu_tpu.storage.deps import query_dependencies
from evolu_tpu.storage.native import open_database
from evolu_tpu.storage.sqlite import PySqliteDatabase
from evolu_tpu.utils.config import Config

MNEMONIC = ("abandon abandon abandon abandon abandon abandon "
            "abandon abandon abandon abandon abandon about")
EMPTY_TREE = merkle_tree_to_string(create_initial_merkle_tree())

SCHEMA_TDS = (
    TableDefinition.of("todo", ("title", "done", "createdAt", "createdBy",
                                "updatedAt", "isDeleted")),
    TableDefinition.of("other", ("name", "createdAt", "createdBy",
                                 "updatedAt", "isDeleted")),
)


def q_str(sql, params=()):
    return msg.serialize_query(sql, params)


def counting_now(base=1_700_000_000_000, step=7):
    c = itertools.count()
    return lambda: base + step * next(c)


def make_worker(**cfg_kw):
    cfg_kw.setdefault("backend", "cpu")
    cfg_kw.setdefault("winner_cache", False)
    db = open_database(":memory:")
    outputs = []
    pushes = []
    w = DbWorker(db, config=Config(**cfg_kw), on_output=outputs.append,
                 post_sync=pushes.append, now=counting_now())
    w.start(MNEMONIC)
    w.stop()  # drive handle() synchronously from here on
    # Pin the (otherwise random) HLC node id so twin workers fed the
    # same schedule stamp identical timestamps.
    from dataclasses import replace

    from evolu_tpu.storage.clock import read_clock, update_clock
    from evolu_tpu.core.types import CrdtClock

    clock = read_clock(db)
    with db.transaction():
        update_clock(db, CrdtClock(
            replace(clock.timestamp, node="00c0ffee00c0ffee"),
            clock.merkle_tree))
    outputs.clear()
    w.handle(msg.UpdateDbSchema(SCHEMA_TDS))
    return w, outputs, pushes


def remote_ts(i, counter=0, node="00000000000000ab", upper=False):
    s = timestamp_to_string(
        Timestamp(1_700_000_000_000 + i, counter, node))
    if upper:
        s = s[:30] + s[30:].upper()
    return s


# --- storage/deps.py -------------------------------------------------


@pytest.fixture(params=["python", "native"])
def dep_db(request):
    if request.param == "native":
        from evolu_tpu.storage.native import native_available

        if not native_available():
            pytest.skip("native backend unavailable")
    db = open_database(":memory:", backend=request.param)
    db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title", "done")')
    db.exec('CREATE TABLE "cat" ("id" TEXT PRIMARY KEY, "name")')
    db.exec('CREATE INDEX "idx_todo_title" ON "todo" ("title")')
    yield db
    db.close()


def test_deps_single_table(dep_db):
    d = query_dependencies(dep_db, 'SELECT "id", "title" FROM "todo" WHERE "done" = ?', (1,))
    assert d.tables == frozenset({"todo"})
    assert d.row_filters == {}


def test_deps_covering_index_maps_to_owning_table(dep_db):
    # Satisfied via idx_todo_title: the cursor opens the INDEX btree;
    # sqlite_master.tbl_name must map it back to "todo".
    d = query_dependencies(dep_db, 'SELECT "title" FROM "todo" ORDER BY "title"', ())
    assert d.tables == frozenset({"todo"})


def test_deps_join_and_subquery(dep_db):
    d = query_dependencies(
        dep_db,
        'SELECT "todo"."id" FROM "todo" inner join "cat" on "cat"."id" = "todo"."done" '
        'WHERE exists (SELECT 1 FROM "cat" WHERE "cat"."name" = ?)',
        ("x",),
    )
    assert d.tables == frozenset({"todo", "cat"})


def test_deps_unknown_for_schema_reads_and_nondeterminism(dep_db):
    assert query_dependencies(
        dep_db, "SELECT name FROM sqlite_master", ()).tables is None
    assert query_dependencies(
        dep_db, 'SELECT "id" FROM "todo" WHERE "done" = random()', ()).tables is None
    assert query_dependencies(
        dep_db, "SELECT CURRENT_TIMESTAMP", ()).tables is None
    # Broken SQL: deps never raise; the execution owns the error.
    assert query_dependencies(dep_db, "SELECT * FROM missing", ()).tables is None


def test_deps_id_row_filters(dep_db):
    d = query_dependencies(dep_db, 'SELECT * FROM "todo" WHERE "id" = ?', ("a",))
    assert d.row_filters == {"todo": frozenset({"a"})}
    d = query_dependencies(
        dep_db, 'SELECT * FROM "todo" WHERE "id" in (?, ?) AND "done" = ?',
        ("a", "b", 1))
    assert d.row_filters == {"todo": frozenset({"a", "b"})}
    # Qualified attribution inside a join; the unconstrained side stays
    # unfiltered (any write to it must re-execute).
    d = query_dependencies(
        dep_db,
        'SELECT "todo"."id" FROM "todo" inner join "cat" on "cat"."id" = "todo"."done" '
        'WHERE "todo"."id" = ?', ("a",))
    assert d.row_filters == {"todo": frozenset({"a"})}
    # Unqualified id in a join is ambiguous: no attribution.
    d = query_dependencies(
        dep_db,
        'SELECT "todo"."title" FROM "todo" inner join "cat" on "cat"."id" = "todo"."done" '
        'WHERE "id" = ?', ("a",))
    assert d.row_filters == {}


def test_deps_row_filter_refuses_unsound_shapes(dep_db):
    # Top-level OR: the id conjunct no longer bounds the row set.
    d = query_dependencies(
        dep_db, 'SELECT * FROM "todo" WHERE ("id" = ? or "done" = ?)', ("a", 1))
    assert d.tables == frozenset({"todo"}) and d.row_filters == {}
    # String literal could hide placeholders: indexing unmappable.
    d = query_dependencies(
        dep_db, 'SELECT * FROM "todo" WHERE "id" = ? AND "title" != \'x?y\'', ("a",))
    assert d.row_filters == {}
    # Predicate-only WHERE (no id): table-level only.
    d = query_dependencies(
        dep_db, 'SELECT * FROM "todo" WHERE "done" is not 1', ())
    assert d.row_filters == {}
    # A subquery can read the SAME table through a second unconstrained
    # cursor: the id conjunct bounds only the outer cursor (review
    # finding — previously skipped row-disjoint writes and left the
    # cached scalar stale forever).
    d = query_dependencies(
        dep_db,
        'SELECT (SELECT count(*) FROM "todo") AS n, "title" FROM "todo" '
        'WHERE "id" = ?', ("a",))
    assert d.tables == frozenset({"todo"}) and d.row_filters == {}
    # Non-str bound values: SQLite TEXT affinity matches id 5 against
    # the row whose id is '5', but set disjointness over {5} vs {'5'}
    # would wrongly skip (review finding).
    d = query_dependencies(
        dep_db, 'SELECT * FROM "todo" WHERE "id" = ?', (5,))
    assert d.row_filters == {}
    d = query_dependencies(
        dep_db, 'SELECT * FROM "todo" WHERE "id" in (?, ?)', ("a", 5))
    assert d.row_filters == {}
    # Self-join: the second, UNCONSTRAINED cursor over the same table
    # makes the qualified id filter unsound (review finding) — the
    # plain join in test_deps_id_row_filters must keep its filter.
    d = query_dependencies(
        dep_db,
        'SELECT "x"."title" FROM "todo" JOIN "todo" AS "x" '
        'ON "x"."done" = "todo"."id" WHERE "todo"."id" = ?', ("a",))
    assert d.tables == frozenset({"todo"}) and d.row_filters == {}


def test_deps_row_filter_refuses_depth0_or(dep_db):
    # AND binds tighter than OR: in `a OR b AND "id" = ?` the id
    # equality is a conjunct of the OR's right arm, NOT of the WHERE
    # (review finding — a write to a row matching `a` changed the
    # result while the row gate skipped re-execution). Any depth-0 OR
    # must drop row filters; table gating still applies.
    d = query_dependencies(
        dep_db,
        'SELECT * FROM "todo" WHERE "done" = ? OR "title" = ? AND "id" = ?',
        ("x", "t", "a"))
    assert d.tables == frozenset({"todo"}) and d.row_filters == {}
    # SQLite tokenizes without surrounding spaces: ' or ' with
    # mandatory spaces misses these (review finding).
    d = query_dependencies(
        dep_db,
        'SELECT * FROM "todo" WHERE "done"=?or"title"=? AND "id" = ?',
        ("x", "t", "a"))
    assert d.tables == frozenset({"todo"}) and d.row_filters == {}
    d = query_dependencies(
        dep_db,
        'SELECT * FROM "todo" WHERE "done" = ?OR("title") = ? AND "id" = ?',
        ("x", "t", "a"))
    assert d.tables == frozenset({"todo"}) and d.row_filters == {}
    # Comment bytes must not feed the scanner: a '(' or '"' inside
    # /*...*/ skews depth/quote tracking past the real OR (review
    # finding). Comments bail outright.
    d = query_dependencies(
        dep_db,
        'SELECT * FROM "todo" WHERE "done" = ? /*(*/ OR /*)*/ '
        '"title" = ? AND "id" = ?', ("x", "t", "ra"))
    assert d.tables == frozenset({"todo"}) and d.row_filters == {}
    d = query_dependencies(
        dep_db,
        'SELECT * FROM "todo" WHERE "done" = ? /*"*/ OR /*"*/ '
        '"title" = ? AND "id" = ?', ("x", "t", "ra"))
    assert d.tables == frozenset({"todo"}) and d.row_filters == {}
    # BETWEEN's AND is an operand separator, not a conjunct boundary:
    # `"a" BETWEEN ? AND "id" = ?` parses as `("a" BETWEEN ? AND "id")
    # = ?` — the id equality is the BETWEEN's upper bound, not a
    # top-level conjunct (review finding). Bail like OR.
    d = query_dependencies(
        dep_db,
        'SELECT * FROM "todo" WHERE "done" BETWEEN ? AND "id" = ?',
        ("x", "ra"))
    assert d.tables == frozenset({"todo"}) and d.row_filters == {}
    d = query_dependencies(
        dep_db,
        'SELECT * FROM "todo" WHERE "done" between ? and ? AND "id" = ?',
        ("a", "z", "ra"))
    assert d.row_filters == {}  # conservative: any depth-0 BETWEEN bails
    # An identifier merely CONTAINING "or" is not the keyword: an
    # unquoted column like `priority` must not trip the bail, and the
    # plain AND-of-equalities shape keeps its filter.
    dep_db.exec('CREATE TABLE "orders" ("id" TEXT PRIMARY KEY, "priority")')
    d = query_dependencies(
        dep_db, 'SELECT * FROM "orders" WHERE priority = ? AND "id" = ?',
        ("x", "a"))
    assert d.row_filters == {"orders": frozenset({"a"})}


def test_deps_zero_arg_datetime_degrades(dep_db):
    # datetime()/date()/time()/julianday()/strftime('%s') default to
    # 'now': clock-dependent with no table write (review finding).
    for fn in ("datetime()", "date()", "julianday()"):
        d = query_dependencies(
            dep_db, f'SELECT "title" FROM "todo" WHERE "title" > {fn}')
        assert d.tables is None, fn


def test_deps_internal_tables_outside_contract_degrade(dep_db):
    # "__clock" is written by update_clock OUTSIDE the apply layer —
    # invisible to the changed-set contract, so reading it must force
    # re-execution (review finding). "__message" IS recorded: gated.
    dep_db.exec('CREATE TABLE "__clock" ("timestamp", "merkle_tree")')
    dep_db.exec('CREATE TABLE "__message" ("timestamp" TEXT PRIMARY KEY)')
    d = query_dependencies(dep_db, 'SELECT "timestamp" FROM "__clock"')
    assert d.tables is None
    d = query_dependencies(dep_db, 'SELECT "timestamp" FROM "__message"')
    assert d.tables == frozenset({"__message"})


# --- storage/changes.py ----------------------------------------------


def test_changed_set_contract():
    c = ChangedSet()
    assert not c
    c.add_cell("t", "r1")
    c.add_cell("t", "r2")
    assert c and c.rows["t"] == {"r1", "r2"}
    c.add_table("t")  # unknown rows dominate
    c.add_cell("t", "r3")
    assert c.rows["t"] is None
    d = ChangedSet()
    d.add_cell("u", "x")
    d.mark_unknown()
    c.merge(d)
    assert c.conservative and c.rows["u"] == {"x"}


def test_changed_set_row_cap_escalates():
    c = ChangedSet()
    for i in range(ROW_SET_CAP + 10):
        c.add_cell("t", f"r{i}")
    assert c.rows["t"] is None  # degraded to all-rows, never dropped


# --- worker gating ----------------------------------------------------


def snap_counters():
    names = ("evolu_query_executed_total", "evolu_query_skipped_clean_total",
             "evolu_query_skipped_by_table_total",
             "evolu_query_skipped_by_rows_total",
             "evolu_query_conservative_total")
    return {n: metrics.get_counter(n) for n in names}


def counter_delta(before, name):
    return metrics.get_counter(name) - before[name]


def test_table_disjoint_and_clean_skips():
    w, outputs, _ = make_worker()
    q = q_str('SELECT "id", "title" FROM "todo" ORDER BY "title"')
    w.handle(msg.Send((NewCrdtMessage("todo", "r1", "title", "a"),), (), (q,)))
    assert any(isinstance(o, msg.OnQuery) for o in outputs)
    outputs.clear()

    before = snap_counters()
    w.handle(msg.Query((q,)))  # nothing changed since: clean skip
    assert counter_delta(before, "evolu_query_skipped_clean_total") == 1
    assert not outputs

    # A write to a DIFFERENT table skips without any read.
    before = snap_counters()
    w.handle(msg.Send((NewCrdtMessage("other", "o1", "name", "x"),), (), (q,)))
    assert counter_delta(before, "evolu_query_skipped_by_table_total") == 1
    assert not any(isinstance(o, msg.OnQuery) for o in outputs)
    outputs.clear()

    # A write to the read table executes and patches.
    before = snap_counters()
    w.handle(msg.Send((NewCrdtMessage("todo", "r1", "title", "b"),), (), (q,)))
    assert counter_delta(before, "evolu_query_executed_total") >= 1
    assert any(isinstance(o, msg.OnQuery) for o in outputs)
    assert w.queries_rows_cache[q][0]["title"] == "b"


def test_row_disjoint_skip_and_overlap():
    w, outputs, _ = make_worker()
    qa = q_str('SELECT "id", "title" FROM "todo" WHERE "id" = ?', ("ra",))
    qb = q_str('SELECT "id", "title" FROM "todo" WHERE "id" = ?', ("rb",))
    w.handle(msg.Send((NewCrdtMessage("todo", "ra", "title", "a"),
                       NewCrdtMessage("todo", "rb", "title", "b")), (), (qa, qb)))
    outputs.clear()

    before = snap_counters()
    w.handle(msg.Send((NewCrdtMessage("todo", "ra", "title", "a2"),), (), (qa, qb)))
    # qb is row-disjoint from the write; qa must execute and patch.
    assert counter_delta(before, "evolu_query_skipped_by_rows_total") == 1
    assert counter_delta(before, "evolu_query_executed_total") == 1
    patches = [o for o in outputs if isinstance(o, msg.OnQuery)]
    assert len(patches) == 1
    assert [p[0] for p in patches[0].queries_patches] == [qa]
    assert w.queries_rows_cache[qa][0]["title"] == "a2"
    assert w.queries_rows_cache[qb][0]["title"] == "b"


def test_or_query_is_not_row_gated():
    # Reviewer repro: WHERE "done" = ? OR "title" = ? AND "id" = ?
    # parses as `done=? OR (title=? AND id=?)` — a write to a DIFFERENT
    # row can flip the OR arm and change the result, so the id equality
    # must not produce a row filter. Pre-fix, the write below was
    # skipped-by-rows and the subscription went permanently stale.
    w, outputs, _ = make_worker()
    q = q_str('SELECT "id", "title" FROM "todo" '
              'WHERE "done" = ? OR "title" = ? AND "id" = ?',
              ("x", "t-other", "ra"))
    w.handle(msg.Send((NewCrdtMessage("todo", "ra", "title", "t-a"),), (), (q,)))
    outputs.clear()

    before = snap_counters()
    w.handle(msg.Send((NewCrdtMessage("todo", "rb", "done", "x"),), (), (q,)))
    assert counter_delta(before, "evolu_query_skipped_by_rows_total") == 0
    assert counter_delta(before, "evolu_query_executed_total") >= 1
    patches = [o for o in outputs if isinstance(o, msg.OnQuery)]
    assert patches, "OR-bearing query wrongly row-gated: stale subscription"
    assert [r["id"] for r in w.queries_rows_cache[q]] == ["rb"]


def test_conservative_paths_always_execute():
    w, outputs, _ = make_worker()
    # Unknown deps (schema read): every mutation re-executes it.
    qm = q_str("SELECT COUNT(*) AS n FROM sqlite_master")
    w.handle(msg.Query((qm,)))
    before = snap_counters()
    w.handle(msg.Send((NewCrdtMessage("todo", "r1", "title", "a"),), (), (qm,)))
    assert counter_delta(before, "evolu_query_conservative_total") == 1
    assert counter_delta(before, "evolu_query_executed_total") == 1

    # UpdateDbSchema marks the change log conservative: even a
    # table-disjoint query must re-execute once afterwards.
    qt = q_str('SELECT "id" FROM "todo" ORDER BY "id"')
    w.handle(msg.Query((qt,)))
    w.handle(msg.UpdateDbSchema(
        (TableDefinition.of("third", ("name",)),)))
    before = snap_counters()
    w.handle(msg.Query((qt,)))
    assert counter_delta(before, "evolu_query_conservative_total") == 1
    assert counter_delta(before, "evolu_query_executed_total") == 1


def test_full_flag_and_sync_refresh_bypass_gating():
    w, outputs, _ = make_worker()
    q = q_str('SELECT "id", "title" FROM "todo" ORDER BY "id"')
    w.handle(msg.Send((NewCrdtMessage("todo", "r1", "title", "a"),), (), (q,)))
    outputs.clear()
    # A FOREIGN write the change log cannot see (another process on a
    # shared DB file in production; direct SQL here).
    w.db.run('UPDATE "todo" SET "title" = ? WHERE "id" = ?', ("foreign", "r1"))
    w.handle(msg.Query((q,)))  # gated: skips, stale cache tolerated
    assert not outputs
    w.handle(msg.Query((q,), full=True))  # bypass: picks the write up
    assert any(isinstance(o, msg.OnQuery) for o in outputs)
    assert w.queries_rows_cache[q][0]["title"] == "foreign"
    outputs.clear()
    # Sync refresh is equally ungated.
    w.db.run('UPDATE "todo" SET "title" = ? WHERE "id" = ?', ("foreign2", "r1"))
    w.handle(msg.Sync((q,)))
    assert any(isinstance(o, msg.OnQuery) for o in outputs)
    assert w.queries_rows_cache[q][0]["title"] == "foreign2"


def test_failed_send_rollback_semantics():
    """Two failure shapes: a Send refused BEFORE any write (wire
    encodability screen) records nothing — the DB is untouched, so a
    clean skip afterwards is correct, not stale. A command that fails
    AFTER its apply recorded changes commits the recorded superset
    (handle()'s failure path), so later sweeps re-verify."""
    w, outputs, _ = make_worker()
    q = q_str('SELECT "id", "title" FROM "todo" ORDER BY "id"')
    w.handle(msg.Send((NewCrdtMessage("todo", "r1", "title", "a"),), (), (q,)))
    outputs.clear()
    w.handle(msg.Send((NewCrdtMessage("todo", "r1", "title", b"bytes"),), (), (q,)))
    assert any(isinstance(o, msg.OnError) for o in outputs)
    outputs.clear()
    before = snap_counters()
    w.handle(msg.Query((q,)))  # pre-write refusal: clean skip is sound
    assert counter_delta(before, "evolu_query_skipped_clean_total") == 1
    assert not outputs

    # Now fail AFTER the apply wrote rows: the whole transaction rolls
    # back, but the recorded changed-set must survive so the next sweep
    # re-executes (it re-reads the unchanged rows and emits nothing —
    # conservative, never stale).
    import evolu_tpu.runtime.worker as worker_mod

    real_update = worker_mod.update_clock

    def explode(db, clock):
        raise RuntimeError("injected post-apply failure")

    worker_mod.update_clock = explode
    try:
        w.handle(msg.Send((NewCrdtMessage("todo", "r1", "title", "c"),), (), (q,)))
    finally:
        worker_mod.update_clock = real_update
    assert any(isinstance(o, msg.OnError) for o in outputs)
    outputs.clear()
    before = snap_counters()
    w.handle(msg.Query((q,)))
    assert counter_delta(before, "evolu_query_executed_total") == 1
    assert not outputs  # rollback: rows unchanged, no patch
    assert w.queries_rows_cache[q][0]["title"] == "a"


# --- LRU bounding (satellite: churned one-shots must not leak) --------


def test_one_shot_query_churn_stays_bounded():
    w, _outputs, _ = make_worker(query_cache_max=8)
    for i in range(200):
        w.handle(msg.Query((q_str(
            'SELECT "id" FROM "todo" WHERE "title" = ?', (f"t{i}",)),)))
    assert len(w.queries_rows_cache) <= 8
    assert len(w.queries_raw_cache) <= 8
    assert len(w._query_deps) <= 16
    assert len(w._query_seen) <= 16
    assert len(w._query_lru) <= 16
    assert metrics.get_counter("evolu_query_cache_evictions_total") > 0


def test_evicted_live_query_self_heals_with_root_replace():
    w, outputs, _ = make_worker(query_cache_max=2)
    qs = [q_str('SELECT "id", "title" FROM "todo" WHERE "id" = ?', (f"r{i}",))
          for i in range(4)]
    w.handle(msg.Send(
        tuple(NewCrdtMessage("todo", f"r{i}", "title", f"t{i}") for i in range(4)),
        (), tuple(qs)))
    # Cap 2: the two least-recently-executed entries were evicted.
    assert len(w.queries_rows_cache) == 2
    outputs.clear()
    # Simulated subscriber state for q0 from the patches so far: rows
    # [r0]. Re-running the evicted q0 must emit a ROOT-REPLACE (index
    # ops against [] would corrupt any live subscriber).
    w.handle(msg.Query((qs[0],)))
    patched = [o for o in outputs if isinstance(o, msg.OnQuery)]
    assert len(patched) == 1
    (q0, ops), = patched[0].queries_patches
    assert q0 == qs[0]
    assert ops[0]["path"] == "" and ops[0]["op"] == "replace"
    assert [r["title"] for r in ops[0]["value"]] == ["t0"]
    # Applying it over ANY stale client state converges.
    assert apply_patch([{"id": "stale", "title": "stale"}], ops) == ops[0]["value"]


def test_evicted_query_going_empty_still_patches():
    """Evict a live query whose cached rows were non-empty, delete
    those rows, re-run — the empty result must still reach subscribers
    as a root-replace (no-baseline executions ALWAYS root-replace, so
    no tombstone bookkeeping can cap out and drop the guarantee)."""
    w, outputs, _ = make_worker(query_cache_max=2)
    q0 = q_str('SELECT "id", "title" FROM "todo" WHERE "isDeleted" is not 1 '
               'AND "id" = ?', ("r0",))
    w.handle(msg.Send((NewCrdtMessage("todo", "r0", "title", "t0"),), (), (q0,)))
    assert w.queries_rows_cache[q0]
    # Churn unrelated queries past the cap to evict q0.
    for i in range(4):
        w.handle(msg.Query((q_str(
            'SELECT "id" FROM "other" WHERE "name" = ?', (f"n{i}",)),)))
    assert q0 not in w.queries_rows_cache
    outputs.clear()
    w.handle(msg.Send((NewCrdtMessage("todo", "r0", "isDeleted", 1),), (), (q0,)))
    patched = [o for o in outputs if isinstance(o, msg.OnQuery)]
    assert len(patched) == 1
    (_q, ops), = patched[0].queries_patches
    assert ops == [{"op": "replace", "path": "", "value": []}]


def test_evict_queries_drops_every_structure():
    w, _outputs, _ = make_worker()
    q = q_str('SELECT "id" FROM "todo"')
    w.handle(msg.Query((q,)))
    assert q in w._query_deps and q in w._query_seen
    w.handle(msg.EvictQueries((q,)))
    for store in (w.queries_rows_cache, w.queries_raw_cache, w._query_deps,
                  w._query_seen, w._query_lru):
        assert q not in store


def test_same_table_subquery_never_skipped_stale():
    """End-to-end pin of the subquery review finding: a detail query
    carrying a scalar subquery over the SAME table must re-execute on
    writes to OTHER rows (its aggregate depends on them)."""
    w, outputs, _ = make_worker()
    q = q_str('SELECT (SELECT count(*) FROM "todo") AS n, "title" '
              'FROM "todo" WHERE "id" = ?', ("ra",))
    w.handle(msg.Send((NewCrdtMessage("todo", "ra", "title", "a"),), (), (q,)))
    assert w.queries_rows_cache[q][0]["n"] == 1
    # A row-disjoint write: the filter-less deps must force re-execution.
    w.handle(msg.Send((NewCrdtMessage("todo", "rb", "title", "b"),), (), (q,)))
    assert w.queries_rows_cache[q][0]["n"] == 2, "stale aggregate delivered"


def test_self_join_never_skipped_stale():
    """End-to-end pin of the self-join review finding: the aliased
    second cursor reads rows the id filter doesn't bound."""
    w, _outputs, _ = make_worker()
    q = q_str('SELECT "x"."title" FROM "todo" JOIN "todo" AS "x" '
              'ON "x"."done" = "todo"."id" WHERE "todo"."id" = ?', ("parent",))
    w.handle(msg.Send((NewCrdtMessage("todo", "parent", "title", "p"),
                       NewCrdtMessage("todo", "child", "title", "c1"),
                       NewCrdtMessage("todo", "child", "done", "parent")),
                      (), (q,)))
    assert [r["title"] for r in w.queries_rows_cache[q]] == ["c1"]
    # Write to the CHILD row (row-disjoint from the 'parent' filter):
    w.handle(msg.Send((NewCrdtMessage("todo", "child", "title", "c2"),), (), (q,)))
    assert [r["title"] for r in w.queries_rows_cache[q]] == ["c2"], \
        "stale self-join result delivered"


def test_clock_query_never_skipped_stale():
    """End-to-end pin of the __clock review finding: update_clock
    writes outside the changed-set contract on every Send."""
    w, _outputs, _ = make_worker()
    q = q_str('SELECT "timestamp" FROM "__clock"')
    w.handle(msg.Send((NewCrdtMessage("todo", "ra", "title", "a"),), (), (q,)))
    t0 = w.queries_rows_cache[q][0]["timestamp"]
    # A table-disjoint app write still advances the clock.
    w.handle(msg.Send((NewCrdtMessage("other", "o1", "name", "n"),), (), (q,)))
    t1 = w.queries_rows_cache[q][0]["timestamp"]
    assert t1 != t0, "stale clock row delivered"
    assert t1 == w.db.exec_sql_query('SELECT "timestamp" FROM "__clock"')[0]["timestamp"]


def test_case_variant_wire_table_never_skipped_stale():
    """End-to-end pin of the identifier-case review finding: SQLite
    resolves a remote message's table "TODO" into the table created as
    "todo", so the changed-set and the read set must fold to one key."""
    w, _outputs, _ = make_worker()
    q = q_str('SELECT "id", "title" FROM "todo" WHERE "id" = ?', ("ra",))
    w.handle(msg.Send((NewCrdtMessage("todo", "ra", "title", "a"),), (), (q,)))
    assert w.queries_rows_cache[q][0]["title"] == "a"
    w.handle(msg.Receive(
        (CrdtMessage(remote_ts(1), "TODO", "ra", "title", "remote"),),
        EMPTY_TREE))
    w.handle(msg.Query((q,)))
    assert w.queries_rows_cache[q][0]["title"] == "remote", \
        "case-variant wire write left the subscription stale"


def test_text_affinity_id_param_never_skipped_stale():
    """End-to-end pin of the TEXT-affinity review finding: `"id" = 5`
    (int param) matches the row whose id is '5'; a write to that row
    must re-execute the subscription."""
    w, outputs, _ = make_worker()
    q = q_str('SELECT "id", "title" FROM "todo" WHERE "id" = ?', (5,))
    w.handle(msg.Send((NewCrdtMessage("todo", "5", "title", "t0"),), (), (q,)))
    assert w.queries_rows_cache[q][0]["title"] == "t0"
    w.handle(msg.Send((NewCrdtMessage("todo", "5", "title", "t1"),), (), (q,)))
    assert w.queries_rows_cache[q][0]["title"] == "t1", "stale row delivered"


# --- satellite: stale-.so no-offsets fallback -------------------------


def test_stale_so_no_offsets_fallback_identical_patches():
    """runtime/worker.py's `offs is None` branch (a stale pre-r5 .so
    returns no offsets): pin that the full-unpack fallback emits
    byte-identical output streams by driving twin workers through the
    same schedule, one with offsets stripped."""
    from evolu_tpu.storage.native import native_available

    if not native_available():
        pytest.skip("native backend unavailable (raw path is native-only)")

    w1, out1, _ = make_worker()
    w2, out2, _ = make_worker()
    real = type(w2.db).exec_sql_query_packed_raw

    def no_offsets(sql, parameters=(), with_offsets=False):
        out = real(w2.db, sql, parameters, with_offsets)
        if with_offsets:
            raw, _offs = out
            return raw, None
        return out

    w2.db.exec_sql_query_packed_raw = no_offsets
    q = q_str('SELECT "id", "title", "done" FROM "todo" ORDER BY "title"')
    schedule = [
        msg.Send(tuple(NewCrdtMessage("todo", f"r{i}", "title", f"t{i:02d}")
                       for i in range(8)), (), (q,)),
        msg.Send((NewCrdtMessage("todo", "r3", "done", 1),), (), (q,)),
        msg.Query((q,)),
        msg.Send((NewCrdtMessage("todo", "r3", "title", "zz"),), (), (q,)),
    ]
    for cmd in schedule:
        w1.handle(cmd)
        w2.handle(cmd)
    assert out1 == out2
    assert w1.queries_rows_cache[q] == w2.queries_rows_cache[q]
    # And the fallback actually engaged (no offsets cached anywhere).
    assert all(e[1] is None for e in w2.queries_raw_cache.values())


# --- acceptance: byte-identity vs the re-run-everything oracle --------


def dual_run(schedule, **cfg_kw):
    """Run `schedule` against a gated worker and the ungated oracle;
    the outputs and end states must match exactly."""
    w_gated, out_gated, push_gated = make_worker(query_invalidation=True, **cfg_kw)
    w_naive, out_naive, push_naive = make_worker(query_invalidation=False, **cfg_kw)
    for cmd in schedule:
        w_gated.handle(cmd)
        w_naive.handle(cmd)
    gated_stream = [o for o in out_gated if not isinstance(o, msg.OnError)]
    naive_stream = [o for o in out_naive if not isinstance(o, msg.OnError)]
    assert gated_stream == naive_stream
    assert ([type(o).__name__ for o in out_gated]
            == [type(o).__name__ for o in out_naive])
    assert push_gated == push_naive
    for sql in ('SELECT * FROM "__message" ORDER BY "timestamp"',
                'SELECT * FROM "todo" ORDER BY "id"',
                'SELECT * FROM "other" ORDER BY "id"'):
        assert w_gated.db.exec(sql) == w_naive.db.exec(sql)
    return w_gated, w_naive


def full_schedule(chunked=False):
    q_list = q_str('SELECT "id", "title", "done" FROM "todo" ORDER BY "title"')
    q_detail = q_str('SELECT "id", "title" FROM "todo" WHERE "id" = ?', ("ra",))
    q_other = q_str('SELECT "id", "name" FROM "other" ORDER BY "id"')
    qs = (q_list, q_detail, q_other)
    remote = tuple(
        CrdtMessage(remote_ts(i, counter=i), "todo", f"rem{i % 3}", "title", f"m{i}")
        for i in range(12 if chunked else 4)
    )
    non_canonical = tuple(
        CrdtMessage(remote_ts(100 + i, counter=i, upper=True),
                    "todo", "ra", "done", i)
        for i in range(3)
    )
    return [
        msg.Send((NewCrdtMessage("todo", "ra", "title", "a"),
                  NewCrdtMessage("todo", "rb", "title", "b")), (), qs),
        msg.Query(qs),
        # table-disjoint for the todo queries
        msg.Send((NewCrdtMessage("other", "o1", "name", "n1"),), (), qs),
        # row-disjoint for q_detail
        msg.Send((NewCrdtMessage("todo", "rb", "done", 1),), ("cb1",), qs),
        msg.Query(qs),
        # remote batch (object or packed route per backend), then the
        # client-style re-run sweep
        msg.Receive(remote, EMPTY_TREE),
        msg.Query(qs),
        # non-canonical case: bounces to the host oracle mid-stream
        msg.Receive(non_canonical, EMPTY_TREE),
        msg.Query(qs),
        # rollback: un-encodable value aborts the Send
        msg.Send((NewCrdtMessage("todo", "ra", "title", b"\x00bytes"),), (), qs),
        msg.Query(qs),
        msg.EvictQueries((q_other,)),
        msg.Query(qs),
        msg.Sync(qs),
    ]


def test_byte_identity_gated_vs_oracle_cpu_backend():
    before = snap_counters()
    dual_run(full_schedule())
    # The gate actually engaged across the schedule.
    assert counter_delta(before, "evolu_query_skipped_by_table_total") > 0
    assert counter_delta(before, "evolu_query_skipped_by_rows_total") > 0
    assert counter_delta(before, "evolu_query_skipped_clean_total") > 0


def test_byte_identity_gated_vs_oracle_device_planner():
    """backend="tpu" routes every batch through the device planner +
    HBM winner cache; the non-canonical batch exercises
    `merge._host_fallback` with cache invalidation mid-schedule."""
    dual_run(full_schedule(), backend="tpu", winner_cache=True)


def test_byte_identity_chunked_receive():
    dual_run(full_schedule(chunked=True), receive_chunk_size=5)


def test_byte_identity_typed_crdt_ops():
    """Typed CRDT materializers report their changed rows (and the
    __crdt_* tables) through the same contract."""
    from evolu_tpu.core.crdt_types import counter_delta as cdelta

    tds = SCHEMA_TDS + (TableDefinition.of("metrics", ("name", "clicks:counter")),)
    q_m = q_str('SELECT "id", "clicks" FROM "metrics" WHERE "id" = ?', ("m1",))
    q_t = q_str('SELECT "id", "title" FROM "todo" ORDER BY "id"')
    schedule = [
        msg.UpdateDbSchema(tds),
        msg.Send((NewCrdtMessage("metrics", "m1", "name", "m"),), (), (q_m, q_t)),
        msg.Send((NewCrdtMessage("metrics", "m1", "clicks", cdelta(3)),), (), (q_m, q_t)),
        msg.Query((q_m, q_t)),
        msg.Send((NewCrdtMessage("metrics", "m1", "clicks", cdelta(-1)),), (), (q_m, q_t)),
        msg.Query((q_m, q_t)),
    ]
    w_gated, _ = dual_run(schedule)
    assert w_gated.queries_rows_cache[q_m][0]["clicks"] == 2


# --- client-level: eviction under live subscriptions ------------------


def test_client_subscriptions_survive_cache_eviction():
    """End-to-end through the Evolu client: with a cache cap smaller
    than the subscription count, every subscriber still converges to
    fresh rows (root-replace self-healing), byte-equal to direct SQL."""
    from evolu_tpu.api.query import table
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"todo": ("title", "done")},
                     config=Config(query_cache_max=2))
    try:
        ids = [e.create("todo", {"title": f"t{i}", "done": 0}) for i in range(5)]
        e.worker.flush()
        qs = [table("todo").select("id", "title", "done")
              .where("id", "=", rid).serialize() for rid in ids]
        for q in qs:
            e.subscribe_query(q)
        e.worker.flush()
        for i, rid in enumerate(ids):
            e.update("todo", rid, {"done": 1})
        e.worker.flush()
        for q, rid in zip(qs, ids):
            sql, params = msg.deserialize_query(q)
            assert e.get_query_rows(q) == e.db.exec_sql_query(sql, params)
            assert e.get_query_rows(q)[0]["done"] == 1
    finally:
        e.dispose()
