"""Relay under concurrency.

The reference deploys behind fly.io with 25 allowed concurrent
connections (examples/server-nodejs/fly.toml services.concurrency) but
never tests concurrent access. Here: many clients hammer the HTTP
relay simultaneously — distinct owners spread over the sharded store
(each shard its own single-writer SQLite), and many writers contending
on ONE owner (the per-database RLock serialization path) with
overlapping duplicate batches exercising the changes==1 Merkle gate
under racing inserts. End state must equal a sequentially-fed oracle.

The latency numbers for this scenario live in
benchmarks/relay_concurrency.py / docs/BENCHMARKS.md.
"""

import threading
import urllib.request

import pytest

from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server.relay import RelayServer, RelayStore, ShardedRelayStore
from evolu_tpu.sync import protocol

BASE = 1_700_000_000_000
FRESH_NODE = "f" * 16  # a node id no message carries (own-msg exclusion no-op)


def _msgs(node: str, start: int, n: int):
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (start + i) * 1000, 0, node)),
            b"ct-%d" % (start + i),
        )
        for i in range(n)
    )


def _post(url: str, req: protocol.SyncRequest) -> protocol.SyncResponse:
    body = protocol.encode_sync_request(req)
    r = urllib.request.urlopen(
        urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/octet-stream"}
        ),
        timeout=30,
    )
    return protocol.decode_sync_response(r.read())


def _run_threads(workers):
    barrier = threading.Barrier(len(workers))
    errors = []

    def wrap(fn):
        try:
            barrier.wait(timeout=30)
            fn()
        except Exception as e:  # noqa: BLE001 - collected and re-raised
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(fn,)) for fn in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "stress thread hung"
    if errors:
        raise errors[0]


def test_25_concurrent_distinct_owners_match_sequential_oracle():
    """25 clients (the fly.io concurrency limit), distinct owners, 3
    rounds each, racing through the ThreadingHTTPServer into the
    sharded store. Every owner's final relay state must be exactly what
    a sequential single-store run produces."""
    server = RelayServer(ShardedRelayStore(shards=4)).start()
    try:
        users = [f"user{i:02d}" for i in range(25)]
        nodes = [f"{i:016x}" for i in range(1, 26)]

        def client(u, node):
            def run():
                for rnd in range(3):
                    req = protocol.SyncRequest(
                        _msgs(node, rnd * 30, 30), u, node, "{}"
                    )
                    resp = _post(server.url, req)
                    assert resp.merkle_tree  # tree always returned
            return run

        _run_threads([client(u, n) for u, n in zip(users, nodes)])

        oracle = RelayStore()
        try:
            for u, node in zip(users, nodes):
                tree = oracle.add_messages(u, _msgs(node, 0, 90))
                got = _post(
                    server.url, protocol.SyncRequest((), u, FRESH_NODE, "{}")
                )
                assert got.merkle_tree == merkle_tree_to_string(tree), u
                assert [m.timestamp for m in got.messages] == [
                    m.timestamp for m in _msgs(node, 0, 90)
                ], u
                assert [m.content for m in got.messages] == [
                    m.content for m in _msgs(node, 0, 90)
                ], u
        finally:
            oracle.close()
    finally:
        server.stop()


def test_single_owner_contention_duplicates_race():
    """8 writers racing on ONE owner through one SQLite handle: each
    posts its own slice plus a shared duplicate slice (every thread
    re-sends messages 0..19). INSERT OR IGNORE + the changes==1 XOR
    gate must keep the tree exact — a duplicate that double-XORed under
    the race would corrupt the digest permanently."""
    server = RelayServer(RelayStore()).start()
    try:
        user = "hot-owner"
        shared = _msgs("a" * 16, 0, 20)

        def writer(i):
            own = _msgs(f"{i + 1:016x}", 100 + i * 20, 20)

            def run():
                _post(server.url, protocol.SyncRequest(shared + own, user, f"{i + 1:016x}", "{}"))
                _post(server.url, protocol.SyncRequest(shared, user, f"{i + 1:016x}", "{}"))
            return run

        _run_threads([writer(i) for i in range(8)])

        oracle = RelayStore()
        try:
            expect = list(shared) + [
                m for i in range(8) for m in _msgs(f"{i + 1:016x}", 100 + i * 20, 20)
            ]
            tree = oracle.add_messages(user, tuple(expect))
            got = _post(server.url, protocol.SyncRequest((), user, FRESH_NODE, "{}"))
            assert got.merkle_tree == merkle_tree_to_string(tree)
            assert sorted(m.timestamp for m in got.messages) == sorted(
                m.timestamp for m in expect
            )
            assert len(got.messages) == len(expect)  # duplicates stored once
        finally:
            oracle.close()
    finally:
        server.stop()


def test_multiprocess_relay_concurrent_clients_consistent(tmp_path):
    """Pre-forked relay (2 worker PROCESSES, one SO_REUSEPORT port,
    shared file-backed WAL store): 12 concurrent clients × 3 rounds
    land every message exactly once, and each user's stored tree
    equals a sequential recompute — regardless of which worker served
    which request (VERDICT r2 #8)."""
    import threading
    import urllib.request

    from evolu_tpu.core.merkle import (
        apply_prefix_xors, create_initial_merkle_tree, merkle_tree_to_string,
        minute_deltas_host,
    )
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.server.relay import MultiprocessRelay, ShardedRelayStore
    from evolu_tpu.sync import protocol

    base = 1_700_000_000_000
    relay = MultiprocessRelay(str(tmp_path / "relay.db"), workers=2, shards=4).start()
    errors = []
    try:
        def post(req):
            body = protocol.encode_sync_request(req)
            with urllib.request.urlopen(
                urllib.request.Request(
                    relay.url, data=body,
                    headers={"Content-Type": "application/octet-stream"},
                ), timeout=30,
            ) as r:
                return protocol.decode_sync_response(r.read())

        def client(i):
            try:
                user, node = f"user{i:02d}", f"{i + 1:016x}"
                for rnd in range(3):
                    msgs = tuple(
                        protocol.EncryptedCrdtMessage(
                            timestamp_to_string(
                                Timestamp(base + (i * 1000 + rnd * 100 + j) * 1000, 0, node)
                            ),
                            b"ct" * 8,
                        )
                        for j in range(40)
                    )
                    post(protocol.SyncRequest(msgs, user, node, "{}"))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,)) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[0]
    finally:
        relay.stop()

    # Inspect the shared store directly: exactly once, trees coherent.
    store = ShardedRelayStore(str(tmp_path / "relay.db"), shards=4)
    try:
        for i in range(12):
            user, node = f"user{i:02d}", f"{i + 1:016x}"
            shard = store.shard_of(user)
            rows = shard.db.exec_sql_query(
                'SELECT "timestamp" FROM "message" WHERE "userId" = ? ORDER BY "timestamp"',
                (user,),
            )
            assert len(rows) == 120, (user, len(rows))
            deltas, _ = minute_deltas_host(r["timestamp"] for r in rows)
            expect = apply_prefix_xors(create_initial_merkle_tree(), deltas)
            assert merkle_tree_to_string(store.get_merkle_tree(user)) == \
                merkle_tree_to_string(expect), user
    finally:
        store.close()


def test_clients_converge_through_multiprocess_relay(tmp_path):
    """Full client sync loops through a 2-worker pre-forked relay:
    whichever worker the kernel hands each connection to, both
    replicas converge byte-identically."""
    import time

    from evolu_tpu.runtime.client import create_evolu
    from evolu_tpu.server.relay import MultiprocessRelay
    from evolu_tpu.sync.client import connect
    from evolu_tpu.utils.config import Config

    relay = MultiprocessRelay(str(tmp_path / "relay.db"), workers=2, shards=4).start()
    a = b = None
    try:
        cfg = Config(sync_url=relay.url + "/")
        a = create_evolu({"todo": ("title",)}, config=cfg)
        b = create_evolu({"todo": ("title",)}, config=cfg, mnemonic=a.owner.mnemonic)
        connect(a)
        connect(b)
        for i in range(20):
            (a if i % 2 else b).create("todo", {"title": f"t{i}"})
        deadline = time.time() + 40
        ok = False
        while time.time() < deadline and not ok:
            for c in (a, b):
                c.sync()
                c.worker.flush()
                c._transport.flush()
                c.worker.flush()
            ra = a.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
            rb = b.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
            ok = len(ra) == 60 and ra == rb
        assert ok, "replicas did not converge through the multiprocess relay"
    finally:
        for c in (a, b):
            if c is not None:
                c.dispose()
        relay.stop()
