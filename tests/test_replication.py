"""Relay↔relay Merkle anti-entropy replication (server/replicate.py).

No reference equivalent — the reference relay is a single node. These
tests pin the extension's contracts: the peer wire codec (ValueError
only on malformed input, like every wire decoder), pull-based
convergence between relays, debounced write-hint propagation, the
bounded peer backoff state machine, scheduler-coalesced ingest, and
the acceptance scenario — a 3-relay cluster with disjoint AND
overlapping owner writes, one peer partitioned mid-gossip by an
injected transport fault, healed, and converging to byte-identical
per-owner Merkle tree strings and identical relay message tables,
with the healed peer's pull transferring ONLY the diverged range
(asserted via the messages-transferred counter)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import metrics
from evolu_tpu.server.relay import RelayServer, RelayStore, ShardedRelayStore
from evolu_tpu.server.replicate import ReplicationManager
from evolu_tpu.sync import protocol

BASE = 1_700_000_000_000
MINUTE = 60_000


def _msgs(node, minute, start, n):
    """`n` messages inside wall-clock minute `minute` (500 ms steps —
    distinct minutes stay distinct Merkle subtrees)."""
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(
                Timestamp(BASE + minute * MINUTE + (start + i) * 500, 0, node)
            ),
            b"ct\x00-%d-%d" % (minute, start + i),
        )
        for i in range(n)
    )


def _post(url, body):
    with urllib.request.urlopen(
        urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/octet-stream"}
        ),
        timeout=30,
    ) as r:
        return r.read()


def _write(url, user, node, msgs):
    _post(url, protocol.encode_sync_request(protocol.SyncRequest(msgs, user, node, "{}")))


def _state(store):
    """Byte-level replica state: per owner, the STORED tree text and
    every message row (timestamp, content) — what must be identical
    across converged peers."""
    return {
        u: (store.get_merkle_tree_string(u), store.replica_messages(u, ""))
        for u in sorted(store.user_ids())
    }


def _wait_converged(stores, owners, deadline_s=30.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        states = [_state(s) for s in stores]
        if set(states[0]) == set(owners) and all(s == states[0] for s in states[1:]):
            return states[0]
        time.sleep(0.05)
    raise AssertionError(
        f"relays did not converge on {sorted(owners)} within {deadline_s}s: "
        f"{[sorted(_state(s)) for s in stores]}"
    )


def _fast_post(url, body):
    from evolu_tpu.sync.client import _http_post

    return _http_post(url, body, retries=0)


class _FaultyTransport:
    """Injectable replication transport implementing a network
    partition: POSTs to blocked URL prefixes raise a connection-level
    URLError before any bytes move (exactly what a dead peer looks
    like to urllib). Toggled mid-run by the fault-injection tests —
    gossip rounds in flight fail at whichever leg they are on."""

    def __init__(self):
        self._blocked = set()
        self._lock = threading.Lock()

    def post(self, url, body):
        with self._lock:
            blocked = any(url.startswith(b) for b in self._blocked)
        if blocked:
            raise urllib.error.URLError("partitioned (fault injection)")
        return _fast_post(url, body)

    def block(self, *urls):
        with self._lock:
            self._blocked.update(urls)

    def heal(self):
        with self._lock:
            self._blocked.clear()


# -- peer wire codec --


def _codec_vectors():
    summary = protocol.ReplicaSummary(
        (("alice", '{"0":{"hash":7},"hash":7}'), ("b\x00ob", "{}"), ("", "")),
        "replica-1",
    )
    pull = protocol.ReplicaPull(
        (("alice", "2023-11-14T22:13:20.000Z-0000-0000000000000000"),), "replica-2"
    )
    resp = protocol.ReplicaPullResponse(
        (
            protocol.OwnerMessages(
                "alice",
                (
                    protocol.EncryptedCrdtMessage("t" * 46, b"\x00\xff\x80 raw\x00"),
                    protocol.EncryptedCrdtMessage("u" * 46, b""),
                ),
                '{"hash":2}',
            ),
            protocol.OwnerMessages("empty-owner", (), "{}"),
        )
    )
    return summary, pull, resp


def test_replica_wire_codec_round_trips():
    summary, pull, resp = _codec_vectors()
    assert protocol.decode_replica_summary(
        protocol.encode_replica_summary(summary)
    ) == summary
    assert protocol.decode_replica_pull(protocol.encode_replica_pull(pull)) == pull
    assert protocol.decode_replica_pull_response(
        protocol.encode_replica_pull_response(resp)
    ) == resp


def test_replica_wire_decoders_raise_valueerror_only():
    """The wire-decoder invariant applies to the peer codec: ANY
    malformed input raises ValueError — never AttributeError /
    TypeError / IndexError — across truncations, bit flips, wrong wire
    types, and random garbage."""
    import random

    summary, pull, resp = _codec_vectors()
    valid = [
        protocol.encode_replica_summary(summary),
        protocol.encode_replica_pull(pull),
        protocol.encode_replica_pull_response(resp),
    ]
    rng = random.Random(7)
    cases = [
        b"\xff", b"\x08", b"\x0a\x05ab",  # truncated varint/field
        b"\x08\x01",  # varint where a message is expected
        b"\x0d\x01\x02\x03\x04",  # fixed32 in field 1
        b"\x0a\x02\x08\x01",  # nested varint owner entry
    ]
    for blob in valid:
        cases.extend(blob[:k] for k in range(1, len(blob), 7))
        for _ in range(40):
            b = bytearray(blob)
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            cases.append(bytes(b))
        cases.extend(bytes(rng.randrange(256) for _ in range(n)) for n in (3, 17, 64))
    decoders = (
        protocol.decode_replica_summary,
        protocol.decode_replica_pull,
        protocol.decode_replica_pull_response,
        protocol.decode_owner_messages,
    )
    for dec in decoders:
        for data in cases:
            try:
                dec(bytes(data))
            except ValueError:
                pass  # the ONLY sanctioned error type


def test_unconfigured_relay_hides_the_replication_surface():
    """A relay WITHOUT replication configured answers 404 on
    /replicate/* — the summary endpoint (and the snapshot manifest)
    enumerate owner ids, which are capabilities on the sync path."""
    server = RelayServer(RelayStore()).start()
    try:
        for path in ("/replicate/summary", "/replicate/pull",
                     "/replicate/snapshot", "/replicate/snapshot/chunk"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(server.url + path, b"")
            assert ei.value.code == 404
    finally:
        server.stop()


def test_malformed_replicate_body_answers_400():
    server = RelayServer(RelayStore(), peers=[]).start()
    try:
        for path in ("/replicate/summary", "/replicate/pull",
                     "/replicate/snapshot/chunk"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(server.url + path, b"\xff\xff\xff")
            assert ei.value.code == 400
        # An unknown configured sub-path stays a 404, not a crash.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.url + "/replicate/nope", b"")
        assert ei.value.code == 404
    finally:
        server.stop()


def _post_raw_content_length(url, path, content_length):
    """POST with an arbitrary (possibly hostile) Content-Length header
    over a raw socket — urllib would refuse to send these."""
    import socket
    from urllib.parse import urlsplit

    parts = urlsplit(url)
    with socket.create_connection((parts.hostname, parts.port), timeout=10) as s:
        req = (
            f"POST {path} HTTP/1.1\r\nHost: {parts.netloc}\r\n"
            f"Content-Length: {content_length}\r\n"
            "Content-Type: application/octet-stream\r\n\r\n"
        )
        s.sendall(req.encode("ascii"))
        s.settimeout(10)
        data = b""
        while b"\r\n" not in data:
            got = s.recv(4096)
            if not got:
                break
            data += got
        status = data.split(b"\r\n", 1)[0].decode("ascii", "replace")
        return int(status.split()[1])


def test_hostile_content_length_answers_400_on_both_handlers():
    """Satellite hardening: a non-numeric Content-Length used to raise
    an uncaught ValueError out of `int(...)` (connection reset), and a
    NEGATIVE value passed the `> MAX_BODY_BYTES` check and then
    `rfile.read(-1)` read UNBOUNDED. Both must answer 400 — on the
    sync handler (do_POST) and the replicate handler alike — and the
    server must stay serviceable afterwards."""
    server = RelayServer(RelayStore(), peers=[]).start()
    try:
        for path in ("/", "/replicate/summary"):
            for hostile in ("banana", "-1", "-999999999", "12abc", ""):
                code = _post_raw_content_length(server.url, path, hostile)
                assert code == 400, (path, hostile, code)
        # Oversize still answers 413 (the cap, distinct from 400).
        for path in ("/", "/replicate/summary"):
            code = _post_raw_content_length(
                server.url, path, 20 * 1024 * 1024 + 1
            )
            assert code == 413, (path, code)
        # The relay still serves normal traffic after the abuse.
        body = protocol.encode_replica_summary(
            protocol.ReplicaSummary((), "probe")
        )
        protocol.decode_replica_summary(
            _post(server.url + "/replicate/summary", body)
        )
    finally:
        server.stop()


# -- convergence --


def test_two_relay_pull_convergence_and_observability_surface():
    """Fresh relay B peers with seeded relay A: one gossip sweep pulls
    everything, trees and message tables converge byte-identically, and
    the replication section shows up in /stats and /metrics."""
    n1, n2 = "1" * 16, "2" * 16
    a = RelayServer(RelayStore(), peers=[]).start()  # listener-only source
    b = None
    try:
        _write(a.url, "alice", n1, _msgs(n1, 0, 0, 40))
        _write(a.url, "bob", n2, _msgs(n2, 0, 0, 30))
        b = RelayServer(RelayStore(), peers=[a.url], replication_interval_s=0.1).start()
        _wait_converged([a.store, b.store], {"alice", "bob"}, deadline_s=20)

        stats = json.loads(_get(b.url + "/stats"))
        (peer,) = stats["replication"]["peers"]
        assert peer["url"] == a.url
        assert peer["healthy"] is True
        assert peer["messages_pulled"] >= 70
        assert "evolu_repl_rounds_total" in _get(b.url + "/metrics").decode()
        # The convergence plane (ISSUE 10): per-(owner, peer) freshness
        # watermarks on the PULLING replica equal the newest HLC millis
        # ingested per owner (rows carry the clock — no new clocks),
        # and the write→visible lag histogram observed once per owner
        # with the ingest trace as its exemplar.
        rid = b.replication.replica_id
        assert metrics.registry.get_gauge(
            "evolu_conv_owner_freshness_millis",
            replica=rid, peer=a.url, owner="alice",
        ) == BASE + 39 * 500
        assert metrics.registry.get_gauge(
            "evolu_conv_owner_freshness_millis",
            replica=rid, peer=a.url, owner="bob",
        ) == BASE + 29 * 500
        hist = metrics.registry.get_histogram(
            "evolu_conv_write_visible_ms", replica=rid, peer=a.url
        )
        assert hist is not None and hist[3] >= 2  # one observe per owner
        assert metrics.registry.get_exemplar(
            "evolu_conv_write_visible_ms", replica=rid, peer=a.url
        ) is not None
        # Convergence-lag: the peer was diverged and this round healed
        # it — the (replica, peer) lag histogram must have fired.
        lag = metrics.registry.get_histogram(
            "evolu_repl_convergence_lag_ms", replica=rid, peer=a.url
        )
        assert lag is not None and lag[3] >= 1
    finally:
        if b is not None:
            b.stop()
        a.stop()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read()


def test_write_hint_propagates_across_peers_without_interval():
    """Both relays' intervals are an hour — propagation must ride the
    debounced hint chain alone: a client write hints the written
    relay, whose summary POST shows the peer divergence, which hints
    the peer's manager into an immediate pull."""
    store_a, store_b = RelayStore(), RelayStore()
    mgr_a = ReplicationManager(
        store_a, [], replica_id="hint-A", interval_s=3600, debounce_s=0.02,
        http_post=_fast_post,
    )
    mgr_b = ReplicationManager(
        store_b, [], replica_id="hint-B", interval_s=3600, debounce_s=0.02,
        http_post=_fast_post,
    )
    a = RelayServer(store_a, replication=mgr_a).start()
    b = RelayServer(store_b, replication=mgr_b).start()
    try:
        mgr_a.add_peer(b.url)
        mgr_b.add_peer(a.url)
        time.sleep(0.2)  # initial empty rounds; next periodic is 1h out
        node = "3" * 16
        _write(a.url, "carol", node, _msgs(node, 1, 0, 20))
        _wait_converged([store_a, store_b], {"carol"}, deadline_s=15)
    finally:
        a.stop()
        b.stop()


def test_hint_chain_propagates_through_a_relay_chain():
    """Chain topology A↔B↔C (no A↔C edge), hour-long intervals: a
    write to A must reach C through B on hint latency alone — B's
    round that PULLS fresh rows re-arms its own hint, so the data
    makes the next hop without waiting out any interval."""
    stores = [RelayStore() for _ in range(3)]
    mgrs = [
        ReplicationManager(
            s, [], replica_id=f"chain-{i}", interval_s=3600, debounce_s=0.02,
            http_post=_fast_post,
        )
        for i, s in enumerate(stores)
    ]
    servers = [RelayServer(s, replication=m).start() for s, m in zip(stores, mgrs)]
    a, b, c = servers
    try:
        mgrs[0].add_peer(b.url)
        mgrs[1].add_peer(c.url)  # B sweeps C FIRST — the adversarial order
        mgrs[1].add_peer(a.url)
        mgrs[2].add_peer(b.url)
        time.sleep(0.3)  # initial empty rounds; next periodic is 1h out
        node = "4" * 16
        _write(a.url, "erin", node, _msgs(node, 2, 0, 18))
        _wait_converged(stores, {"erin"}, deadline_s=15)
    finally:
        for srv in servers:
            srv.stop()


def test_three_relay_partition_heal_convergence():
    """The acceptance scenario. Full-mesh A/B/C with disjoint + an
    overlapping owner; C is partitioned mid-gossip (transport fault
    injection, both directions), A/B keep converging; after heal all
    three reach byte-identical per-owner tree strings and identical
    message tables — and C's pull transferred ONLY the diverged range
    (messages-transferred counter delta == partition-era rows, a
    fraction of the full DB)."""
    n1, n2, n3 = "1" * 16, "2" * 16, "3" * 16
    stores = [RelayStore(), RelayStore(), ShardedRelayStore(shards=2)]
    faults = [_FaultyTransport() for _ in range(3)]
    names = ["part-A", "part-B", "part-C"]
    mgrs = [
        ReplicationManager(
            s, [], replica_id=name, interval_s=0.1, debounce_s=0.02,
            backoff_base_s=0.05, backoff_max_s=0.5, http_post=f.post,
        )
        for s, f, name in zip(stores, faults, names)
    ]
    servers = [RelayServer(s, replication=m).start() for s, m in zip(stores, mgrs)]
    a, b, c = servers
    try:
        for i, m in enumerate(mgrs):
            for j, srv in enumerate(servers):
                if i != j:
                    m.add_peer(srv.url)

        # Phase 1 — pre-partition history (minute 0): "alice" written
        # on BOTH A and C (overlapping owner, distinct nodes), "bob"
        # only on B (disjoint). Cluster converges.
        _write(a.url, "alice", n1, _msgs(n1, 0, 0, 30))
        _write(c.url, "alice", n3, _msgs(n3, 0, 0, 20))
        _write(b.url, "bob", n2, _msgs(n2, 0, 0, 25))
        _wait_converged(stores, {"alice", "bob"})
        total_rows_before = sum(
            len(rows) for _t, rows in _state(stores[0]).values()
        )
        assert total_rows_before == 75

        # Phase 2 — partition C mid-gossip, both directions.
        faults[0].block(c.url)
        faults[1].block(c.url)
        faults[2].block(a.url, b.url)
        fail0 = metrics.get_counter(
            "evolu_repl_peer_failures_total", replica="part-C", peer=a.url
        )
        # Partition-era writes (minute 5) land on A and B only:
        # "alice" grows on A (the overlapping owner diverges), "dave"
        # is born on B (an owner C has never seen).
        _write(a.url, "alice", n1, _msgs(n1, 5, 0, 15))
        _write(b.url, "dave", n2, _msgs(n2, 5, 0, 10))
        _wait_converged(stores[:2], {"alice", "bob", "dave"})
        deadline = time.time() + 10
        while (
            metrics.get_counter(
                "evolu_repl_peer_failures_total", replica="part-C", peer=a.url
            )
            <= fail0
            and time.time() < deadline
        ):
            time.sleep(0.02)
        assert metrics.get_counter(
            "evolu_repl_peer_failures_total", replica="part-C", peer=a.url
        ) > fail0, "partitioned peer never observed a failed round"
        assert metrics.registry.get_gauge(
            "evolu_repl_peer_healthy", replica="part-C", peer=a.url
        ) == 0
        # C still serves its pre-partition state.
        assert set(_state(stores[2])) == {"alice", "bob"}

        pulled_before = sum(
            metrics.get_counter(
                "evolu_repl_messages_pulled_total", replica="part-C", peer=srv.url
            )
            for srv in (a, b)
        )

        # Phase 3 — heal. Everything converges byte-identically.
        for f in faults:
            f.heal()
        final = _wait_converged(stores, {"alice", "bob", "dave"})
        for owner, (tree_s, rows) in final.items():
            assert tree_s != "{}", owner
            assert rows, owner

        # The healed peer transferred ONLY the diverged range: the 25
        # partition-era rows — not the 75-row pre-partition history it
        # already held (counter delta, NOT full-DB row count).
        pulled_delta = sum(
            metrics.get_counter(
                "evolu_repl_messages_pulled_total", replica="part-C", peer=srv.url
            )
            for srv in (a, b)
        ) - pulled_before
        assert pulled_delta == 25, pulled_delta
        total_rows_after = sum(len(rows) for _t, rows in final.values())
        assert total_rows_after == 100
        assert pulled_delta < total_rows_after

        # Recovery is visible: health back to 1, and the convergence
        # lag histogram recorded the partition's heal. Data convergence
        # can land via the round against ONE peer while the other
        # peer's round still sits in its (bounded ≤0.5s) backoff — poll
        # briefly instead of racing the state machine.
        def _recovered():
            healthy = metrics.registry.get_gauge(
                "evolu_repl_peer_healthy", replica="part-C", peer=a.url
            )
            lag_count = sum(
                (metrics.registry.get_histogram(
                    "evolu_repl_convergence_lag_ms", replica="part-C", peer=srv.url
                ) or (None, None, 0.0, 0))[3]
                for srv in (a, b)
            )
            return healthy == 1 and lag_count >= 1

        deadline = time.time() + 10
        while time.time() < deadline and not _recovered():
            time.sleep(0.02)
        assert _recovered(), "healed peer's health/lag telemetry never recovered"
    finally:
        for srv in servers:
            srv.stop()


def test_capped_pull_catches_up_incrementally(monkeypatch):
    """A deep catch-up never ships one giant response: serve_pull caps
    messages per owner (and per response), a truncated pull leaves the
    trees differing, and successive rounds resume from the advanced
    diff minute until convergence — bounded transfer per round, exact
    total (idempotent ingest, no double-XOR)."""
    from evolu_tpu.server import replicate

    monkeypatch.setattr(replicate, "PULL_MESSAGES_PER_OWNER", 40)
    monkeypatch.setattr(replicate, "PULL_MESSAGES_PER_RESPONSE", 60)
    n1, n2 = "1" * 16, "2" * 16
    src = RelayServer(RelayStore(), peers=[]).start()
    dest = RelayStore()
    mgr = None
    try:
        # 2 owners × 6 minutes × 20 = 240 rows to catch up on.
        for u, node in (("deep-a", n1), ("deep-b", n2)):
            for minute in range(6):
                src.store.add_messages(u, _msgs(node, minute, 0, 20))
        mgr = ReplicationManager(
            dest, [src.url], replica_id="capped-R", http_post=_fast_post,
        )
        per_round = []
        for _ in range(12):
            before = metrics.get_counter(
                "evolu_repl_messages_pulled_total", replica="capped-R", peer=src.url
            )
            mgr.run_once()
            pulled = metrics.get_counter(
                "evolu_repl_messages_pulled_total", replica="capped-R", peer=src.url
            ) - before
            per_round.append(pulled)
            if _state(dest) == _state(src.store):
                break
        assert _state(dest) == _state(src.store), per_round
        assert max(per_round) <= 60, per_round  # response budget held
        assert sum(per_round) == 240, per_round  # exact, no re-pulls
        assert len([p for p in per_round if p]) >= 4  # genuinely incremental
    finally:
        if mgr is not None:
            mgr.stop()
        dest.close()
        src.stop()


def test_peer_failure_backoff_bounded_exponential_with_recovery():
    """Consecutive failures grow the retry delay exponentially under a
    hard cap (jitter pinned via the injectable rng); the first
    successful round resets the state machine and the health gauge."""
    target = RelayServer(RelayStore(), peers=[]).start()
    store = RelayStore()
    fault = _FaultyTransport()
    fault.block(target.url)
    mgr = ReplicationManager(
        store, [target.url], replica_id="backoff-X", interval_s=60,
        backoff_base_s=0.05, backoff_max_s=1.0, http_post=fault.post,
        rng=lambda: 1.0,  # jitter factor pinned to 1.0 → deterministic
    )
    peer = mgr._peers[0]
    try:
        delays = []
        for _ in range(7):
            mgr.run_once()
            delays.append(peer.next_due - time.monotonic())
        assert peer.failures == 7
        assert delays[0] < delays[1] < delays[2], delays
        assert all(d <= 1.0 + 1e-6 for d in delays), delays  # hard cap
        assert metrics.get_counter(
            "evolu_repl_peer_failures_total", replica="backoff-X", peer=target.url
        ) == 7
        assert metrics.registry.get_gauge(
            "evolu_repl_peer_healthy", replica="backoff-X", peer=target.url
        ) == 0

        fault.heal()
        mgr.run_once()
        assert peer.failures == 0
        assert metrics.registry.get_gauge(
            "evolu_repl_peer_healthy", replica="backoff-X", peer=target.url
        ) == 1
        assert metrics.get_counter(
            "evolu_repl_rounds_total", replica="backoff-X", peer=target.url,
            result="ok",
        ) >= 1
    finally:
        mgr.stop()
        target.stop()
        store.close()


def test_replication_ingest_coalesces_through_the_scheduler():
    """On a batching relay the pulled messages are submitted through
    the PR-2 scheduler: every replication request rides a fused engine
    pass (coalesced-requests counter), in FEWER passes than requests —
    replication traffic shares the live-traffic batcher."""
    src = RelayServer(ShardedRelayStore(shards=2), peers=[]).start()
    dst_store = ShardedRelayStore(shards=2)
    dst = RelayServer(dst_store, batching=True).start()
    mgr = None
    try:
        owners = {f"sched-u{i}": f"{i + 1:016x}" for i in range(10)}
        for u, node in owners.items():
            src.store.add_messages(u, _msgs(node, 0, 0, 20))
        mgr = ReplicationManager(
            dst_store, [src.url], replica_id="sched-R", scheduler=dst.scheduler,
            http_post=_fast_post,
        )
        batches0 = metrics.get_counter("evolu_sched_batches_total")
        coalesced0 = metrics.get_counter("evolu_sched_coalesced_requests_total")
        mgr.run_once()
        _wait_converged([src.store, dst_store], set(owners), deadline_s=20)
        coalesced = metrics.get_counter("evolu_sched_coalesced_requests_total") - coalesced0
        batches = metrics.get_counter("evolu_sched_batches_total") - batches0
        assert coalesced == len(owners), (coalesced, batches)
        assert 1 <= batches <= len(owners)
    finally:
        if mgr is not None:
            mgr.stop()
        dst.stop()
        src.stop()
