"""Runtime tests: patches, query builder, model casts, and the client
engine end-to-end (mutate → reactive rows → two-replica convergence).

The reference has no tests at this layer (SURVEY.md §4 — unit tests
cover only the pure CRDT core); these go beyond it per the build plan.
"""

import datetime

import pytest

from evolu_tpu.api import model
from evolu_tpu.api.query import table
from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.runtime import messages as msg
from evolu_tpu.runtime.client import Evolu, create_evolu
from evolu_tpu.runtime.jsonpatch import apply_patch, create_patch

TODO_SCHEMA = {"todo": ("title", "isCompleted", *model.COMMON_COLUMNS)}


def make_client(**kw):
    return create_evolu(TODO_SCHEMA, **kw)


# --- jsonpatch ---


def test_patch_roundtrip_and_identity():
    prev = [{"id": "a", "v": 1}, {"id": "b", "v": 2}, {"id": "c", "v": 3}]
    next_ = [{"id": "a", "v": 1}, {"id": "b", "v": 9}]
    ops = create_patch(prev, next_)
    out = apply_patch(prev, ops)
    assert out == next_
    assert out[0] is prev[0]  # unchanged row keeps identity (db.ts:96-115)


def test_patch_empty_means_no_change():
    rows = [{"id": "a"}]
    assert create_patch(rows, [{"id": "a"}]) == []
    assert create_patch([], []) == []


def test_patch_add_and_remove():
    assert apply_patch([], create_patch([], [{"x": 1}, {"x": 2}])) == [{"x": 1}, {"x": 2}]
    assert apply_patch([{"x": 1}, {"x": 2}], create_patch([{"x": 1}, {"x": 2}], [])) == []


# --- query builder ---


def test_query_builder_compile():
    sql, params = (
        table("todo")
        .select("id", "title")
        .where("isCompleted", "=", 0)
        .where_is_deleted(False)
        .order_by("createdAt")
        .limit(10)
        .compile()
    )
    assert sql == (
        'SELECT "id", "title" FROM "todo" WHERE "isCompleted" = ? '
        'AND "isDeleted" is not ? ORDER BY "createdAt" asc LIMIT ?'
    )
    assert params == [0, 1, 10]


def test_query_builder_rejects_bad_operator():
    with pytest.raises(ValueError):
        table("todo").where("title", "; DROP TABLE", 1)


def test_query_builder_quotes_identifiers():
    sql, _ = table('t"x').select('c"ol').compile()
    assert '"t""x"' in sql and '"c""ol"' in sql


def test_query_builder_joins_and_aliases():
    """The Kysely innerJoin/leftJoin surface (kysely.ts exposes the full
    Kysely select builder; reference apps join e.g. todo to
    todoCategory)."""
    sql, params = (
        table("todo")
        .select(("todo.title", "title"), ("todoCategory.name", "category"))
        .inner_join("todoCategory", "todoCategory.id", "todo.categoryId")
        .where("todo.isDeleted", "is not", 1)
        .order_by("todo.title")
        .compile()
    )
    assert sql == (
        'SELECT "todo"."title" as "title", "todoCategory"."name" as "category" '
        'FROM "todo" inner join "todoCategory" '
        'on "todoCategory"."id" = "todo"."categoryId" '
        'WHERE "todo"."isDeleted" is not ? ORDER BY "todo"."title" asc'
    )
    assert params == [1]
    left, _ = (
        table("todo")
        .left_join("todoCategory", "todoCategory.id", "todo.categoryId")
        .compile()
    )
    assert 'left join "todoCategory"' in left


def test_query_builder_aggregates_group_by_having():
    from evolu_tpu.api.query import fn

    sql, params = (
        table("todo")
        .select("categoryId", fn.count("id").as_("n"), fn.min("createdAt").as_("first"))
        .group_by("categoryId")
        .having(fn.count("id"), ">", 1)
        .order_by("n", "desc")
        .compile()
    )
    assert sql == (
        'SELECT "categoryId", count("id") as "n", min("createdAt") as "first" '
        'FROM "todo" GROUP BY "categoryId" HAVING count("id") > ? '
        'ORDER BY "n" desc'
    )
    assert params == [1]
    assert fn.count().sql() == "count(*)"
    assert fn.count("id", distinct=True).sql() == 'count(distinct "id")'
    # Reusing the selected-and-aliased Fn in having() must not leak the
    # alias into the HAVING clause (invalid SQL).
    n = fn.count("id").as_("n")
    sql2, _ = table("todo").select("categoryId", n).group_by("categoryId").having(n, ">", 1).compile()
    assert 'HAVING count("id") > ?' in sql2
    with pytest.raises(ValueError):
        table("t").having(fn.count(), ">", 0).compile()  # having without group_by
    with pytest.raises(ValueError):
        fn.sum(None)
    with pytest.raises(ValueError):
        fn.count(distinct=True)  # count(distinct *) is invalid SQLite


def test_predicate_expression_trees():
    """OR/AND combinator groups and NOT — the Kysely `eb.or([...])` /
    `eb.and([...])` / `eb.not(...)` surface (types.ts:188-280)."""
    from evolu_tpu.api.query import and_, c, not_, or_

    sql, params = (
        table("todo")
        .select("id")
        .where(or_(
            and_(("isCompleted", "=", 1), ("isDeleted", "is not", 1)),
            c("title", "like", "urgent%"),
        ))
        .compile()
    )
    assert sql == (
        'SELECT "id" FROM "todo" WHERE '
        '(("isCompleted" = ? and "isDeleted" is not ?) or "title" like ?)'
    )
    assert params == [1, 1, "urgent%"]

    # Operator sugar builds the same tree.
    expr = (c("a", "=", 1) & c("b", "=", 2)) | ~c("c", "is", None)
    sql2, params2 = table("t").where(expr).compile()
    assert sql2 == (
        'SELECT * FROM "t" WHERE (("a" = ? and "b" = ?) or not ("c" is null))'
    )
    assert params2 == [1, 2]

    # Chained where() calls still AND with tree terms.
    sql3, params3 = (
        table("t").where("x", "=", 1).where(not_(("y", ">", 2))).compile()
    )
    assert sql3 == 'SELECT * FROM "t" WHERE "x" = ? AND not ("y" > ?)'
    assert params3 == [1, 2]

    with pytest.raises(ValueError):
        or_()
    with pytest.raises(ValueError):
        and_("not-a-condition")
    # A forgotten value must fail at build time, not bind NULL (which
    # would make the subscribed query silently empty).
    with pytest.raises(ValueError):
        table("t").where("isCompleted", "=")
    with pytest.raises(ValueError):
        c("col", "in")
    # ...while an EXPLICIT None still compiles to a null comparison.
    sql_null, _ = table("t").where("x", "is", None).compile()
    assert sql_null.endswith('"x" is null')


def test_subqueries_exists_and_in():
    """`exists(selectFrom(...))` (correlated via ref()) and
    `in`-subqueries, with bound-parameter order matching placeholder
    order across the nesting."""
    from evolu_tpu.api.query import c, exists, not_exists, ref

    sub = (
        table("todoCategory")
        .select("id")
        .where(c("todoCategory.id", "=", ref("todo.categoryId")))
    )
    sql, params = table("todo").select("title").where(exists(sub)).compile()
    assert sql == (
        'SELECT "title" FROM "todo" WHERE exists ('
        'SELECT "id" FROM "todoCategory" '
        'WHERE "todoCategory"."id" = "todo"."categoryId")'
    )
    assert params == []

    sql2, _ = table("todo").where(not_exists(sub)).compile()
    assert 'not exists (' in sql2

    # in-subquery with its own parameter, sandwiched between outer
    # parameters: order must be left-to-right.
    inner = table("todoCategory").select("id").where("name", "=", "work")
    sql3, params3 = (
        table("todo")
        .select("title")
        .where("isDeleted", "is not", 1)
        .where(c("categoryId", "in", inner))
        .where("isCompleted", "=", 0)
        .compile()
    )
    assert sql3 == (
        'SELECT "title" FROM "todo" WHERE "isDeleted" is not ? '
        'AND "categoryId" in (SELECT "id" FROM "todoCategory" WHERE "name" = ?) '
        'AND "isCompleted" = ?'
    )
    assert params3 == [1, "work", 0]


def test_in_with_empty_sequence_compiles_to_constant_false():
    """SQLite rejects `x in ()` at parse time; an empty list must
    compile to a constant-false predicate at build time instead of a
    syntax error at first execution (and `~` must still negate it)."""
    from evolu_tpu.api.query import c, not_

    sql, params = table("todo").select("id").where(c("id", "in", [])).compile()
    assert sql == 'SELECT "id" FROM "todo" WHERE 1 = 0'
    assert params == []

    sql2, _ = table("todo").where(not_(c("id", "in", ()))).compile()
    assert "not (1 = 0)" in sql2

    # And it must actually execute.
    import sqlite3

    conn = sqlite3.connect(":memory:")
    conn.execute('CREATE TABLE "todo" ("id" TEXT)')
    conn.execute('INSERT INTO "todo" VALUES (\'a\')')
    assert conn.execute(sql).fetchall() == []
    conn.close()


def test_reactive_raw_short_circuit_lifecycle():
    """Hot loop #4 (r4): the worker detects unchanged subscribed
    queries by raw packed bytes. The lifecycle must mirror the rows
    cache exactly: a relevant mutation still patches; an irrelevant
    one emits nothing; EVICTION drops the raw entry too (else a
    re-subscribe would be silently skipped and the fresh subscriber
    would never get its add-patch); owner restore clears it."""
    from evolu_tpu.storage.native import native_available

    if not native_available():
        pytest.skip("native backend unavailable (raw path is native-only)")
    import evolu_tpu.runtime.messages as m

    events = []
    e = create_evolu(TODO_SCHEMA)
    assert hasattr(e.worker.db, "exec_sql_query_packed_raw")
    rid = e.create("todo", {"title": "a"})
    e.worker.flush()
    q = table("todo").select("id", "title").order_by("title").serialize()
    # A LIVE subscription (query_once would evict its query, dropping
    # the raw cache and defeating the short-circuit under test).
    e.subscribe_query(q, lambda: events.append(1))
    e.worker.flush()
    assert q in e.worker.queries_raw_cache
    fired_initial = len(events)
    assert fired_initial >= 1  # the initial add-patch reached the app

    # Unchanged re-run: no patch posted (raw equal short-circuit),
    # listener silent, rows identity kept.
    before = dict(e.worker.queries_rows_cache)
    e.worker.post(m.Query((q,)))
    e.worker.flush()
    assert len(events) == fired_initial, "unchanged query must not notify"
    assert e.worker.queries_rows_cache[q] is before[q], "rows identity kept"

    # Relevant mutation: patch must flow (no false skip).
    e.update("todo", rid, {"title": "b"})
    e.worker.flush()
    e.worker.post(m.Query((q,)))
    e.worker.flush()
    assert len(events) > fired_initial, "changed query must notify"
    assert [r["title"] for r in e.worker.queries_rows_cache[q]] == ["b"]

    # Eviction drops BOTH caches; a later re-query rebuilds from scratch.
    e.worker.post(m.EvictQueries((q,)))
    e.worker.flush()
    assert q not in e.worker.queries_raw_cache
    assert q not in e.worker.queries_rows_cache
    e.worker.post(m.Query((q,)))
    e.worker.flush()
    assert [r["title"] for r in e.worker.queries_rows_cache[q]] == ["b"]

    # Owner restore wipes the raw cache with the rows cache.
    e.restore_owner(e.owner.mnemonic)
    e.worker.flush()
    assert e.worker.queries_raw_cache == {}
    e.dispose()


def test_changed_query_reuses_unchanged_row_objects():
    """r5 row-granular unpack in the live worker: after a one-row
    mutation of a multi-row subscribed query, the re-executed rows must
    (a) be correct, (b) REUSE the previous dict objects for every
    unchanged row (identity stability feeds both the differ's `is`
    shortcut and subscribers' referential equality), and (c) emit
    exactly one replace patch."""
    from evolu_tpu.storage.native import native_available

    if not native_available():
        pytest.skip("native backend unavailable (raw path is native-only)")
    import evolu_tpu.runtime.messages as m

    e = create_evolu(TODO_SCHEMA)
    ids = []
    with e.batching():
        for i in range(50):
            ids.append(e.create("todo", {"title": f"item {i:03d}", "isCompleted": 0}))
    e.worker.flush()
    q = table("todo").select("id", "title", "isCompleted").order_by("title").serialize()
    e.subscribe_query(q, lambda: None)
    e.worker.flush()
    before = e.worker.queries_rows_cache[q]
    assert len(before) == 50

    # In-place flag toggle on one mid-result row (sort key unchanged).
    e.update("todo", ids[25], {"isCompleted": 1})
    e.worker.flush()
    e.worker.post(m.Query((q,)))
    e.worker.flush()
    after = e.worker.queries_rows_cache[q]
    assert [r["isCompleted"] for r in after].count(1) == 1
    # updatedAt also changes for the mutated row; every OTHER row must
    # be the SAME object as before.
    reused = sum(1 for a, b in zip(after, before) if a is b)
    assert reused == 49, reused


def test_byte_equality_is_exact_because_nan_cannot_be_stored():
    """Why raw-byte change detection is EXACT, not approximate: the
    one value where byte-equality and deep-equality could diverge is
    REAL NaN (NaN != NaN would make the reference's deep-equal churn,
    query.ts:43-57) — but SQLite converts NaN to NULL at bind time on
    every backend, so no queried row can ever hold one. This pins that
    premise; if a backend ever starts storing NaN, the byte detector
    needs a second look."""
    from evolu_tpu.storage.native import native_available
    from evolu_tpu.storage.sqlite import PySqliteDatabase

    backends = [PySqliteDatabase()]
    if native_available():
        from evolu_tpu.storage.native import CppSqliteDatabase

        backends.append(CppSqliteDatabase())
    for db in backends:
        db.exec('CREATE TABLE "t" ("x")')
        db.run('INSERT INTO "t" VALUES (?)', (float("nan"),))
        assert db.exec_sql_query('SELECT "x" FROM "t"') == [{"x": None}]
        db.close()


# --- model casts (model.ts:100-112) ---


def test_cast_bool_and_date_roundtrip():
    assert model.cast(True) == 1 and model.cast(False) == 0
    assert model.cast(1) is True and model.cast(0) is False
    d = datetime.datetime(2024, 5, 1, 12, 30, 15, 123000, tzinfo=datetime.timezone.utc)
    iso = model.cast(d)
    assert iso == "2024-05-01T12:30:15.123Z"
    assert model.cast(iso) == d


def test_string_validators():
    assert model.validate_string_1000("x" * 1000) == "x" * 1000
    with pytest.raises(Exception):
        model.validate_string_1000("x" * 1001)
    with pytest.raises(Exception):
        model.validate_non_empty_string_1000("   ")


# --- client end-to-end (single replica, no transport) ---


def test_mutate_and_reactive_query():
    evolu = make_client()
    try:
        q = table("todo").select("id", "title").order_by("createdAt").serialize()
        seen = []
        evolu.subscribe_query(q, listener=lambda: seen.append(True))
        row_id = evolu.create("todo", {"title": "buy milk", "isCompleted": False})
        evolu.worker.flush()
        rows = evolu.get_query_rows(q)
        assert [r["title"] for r in rows] == ["buy milk"]
        assert rows[0]["id"] == row_id
        assert seen  # listener fired
    finally:
        evolu.dispose()


def test_update_keeps_unrelated_row_identity():
    evolu = make_client()
    try:
        q = table("todo").select("id", "title").order_by("id").serialize()
        evolu.subscribe_query(q)
        a = evolu.create("todo", {"title": "a"})
        b = evolu.create("todo", {"title": "b"})
        evolu.worker.flush()
        before = {r["id"]: r for r in evolu.get_query_rows(q)}
        evolu.update("todo", b, {"title": "b2"})
        evolu.worker.flush()
        after = {r["id"]: r for r in evolu.get_query_rows(q)}
        assert after[b]["title"] == "b2"
        assert after[a] is before[a]  # identity stable
    finally:
        evolu.dispose()


def test_auto_columns_and_soft_delete():
    evolu = make_client()
    try:
        q = table("todo").select_all().serialize()
        evolu.subscribe_query(q)
        rid = evolu.create("todo", {"title": "t"})
        evolu.worker.flush()
        row = evolu.get_query_rows(q)[0]
        assert row["createdBy"] == evolu.owner.id
        assert model.is_sqlite_date(row["createdAt"])
        assert row["updatedAt"] is None and row["isDeleted"] is None
        evolu.update("todo", rid, {"isDeleted": True})
        evolu.worker.flush()
        row = evolu.get_query_rows(q)[0]
        assert row["isDeleted"] == 1 and model.is_sqlite_date(row["updatedAt"])
    finally:
        evolu.dispose()


def test_batching_coalesces_sends():
    evolu = make_client()
    try:
        sends = []
        evolu.worker.post_sync = lambda r: sends.append(r)
        with evolu.batching():
            evolu.create("todo", {"title": "a"})
            evolu.create("todo", {"title": "b"})
        evolu.worker.flush()
        assert len(sends) == 1
        # one message per column: title + createdAt + createdBy, twice
        assert len(sends[0].messages) == 6
    finally:
        evolu.dispose()


def test_on_complete_runs_after_commit():
    evolu = make_client()
    try:
        done = []
        evolu.create("todo", {"title": "x"}, on_complete=lambda: done.append(True))
        evolu.worker.flush()
        assert done == [True]
    finally:
        evolu.dispose()


def test_error_channel():
    evolu = make_client()
    try:
        errors = []
        evolu.subscribe_error(errors.append)
        evolu.worker.post(msg.Query((msg.serialize_query("SELECT nonsense FROM nowhere"),)))
        evolu.worker.flush()
        assert errors and evolu.get_error() is errors[0]
    finally:
        evolu.dispose()


def test_reset_owner_wipes_and_reloads():
    evolu = make_client()
    try:
        reloaded = []
        evolu.on_reload(lambda: reloaded.append(True))
        evolu.create("todo", {"title": "x"})
        evolu.worker.flush()
        evolu.reset_owner()
        evolu.worker.flush()
        assert reloaded == [True]
        assert evolu.db.exec_sql_query("SELECT name FROM sqlite_schema WHERE type='table'") == []
    finally:
        evolu.dispose()


def test_restore_owner_reseeds_identity():
    evolu = make_client()
    try:
        from evolu_tpu.core.mnemonic import generate_mnemonic
        from evolu_tpu.core.ids import mnemonic_to_owner_id

        m = generate_mnemonic()
        evolu.restore_owner(m)
        evolu.worker.flush()
        assert evolu.worker.owner.id == mnemonic_to_owner_id(m)
        with pytest.raises(Exception):
            evolu.restore_owner("not a mnemonic at all")
    finally:
        evolu.dispose()


# --- two replicas converge by exchanging Receive commands directly ---


def _drain_messages(evolu, for_replica):
    """All of `evolu`'s messages except those authored by `for_replica` —
    the relay's own-message exclusion (apps/server/src/index.ts:100):
    feeding a replica its own timestamps back would raise
    TimestampDuplicateNodeError by design (timestamp.ts:147-153)."""
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.storage.clock import read_clock

    node = read_clock(for_replica.db).timestamp.node
    rows = evolu.db.exec_sql_query(
        'SELECT * FROM "__message" WHERE "timestamp" NOT LIKE \'%\' || ? ORDER BY "timestamp"',
        (node,),
    )
    return tuple(
        CrdtMessage(r["timestamp"], r["table"], r["row"], r["column"], r["value"]) for r in rows
    )


def _tree_string(evolu):
    from evolu_tpu.storage.clock import read_clock

    return merkle_tree_to_string(read_clock(evolu.db).merkle_tree)


def test_two_replicas_converge_via_receive():
    a, b = make_client(), make_client()
    try:
        q = table("todo").select("id", "title").order_by("id").serialize()
        a.subscribe_query(q)
        b.subscribe_query(q)
        rid = a.create("todo", {"title": "from-a"})
        a.worker.flush()
        b.create("todo", {"title": "from-b"})
        b.worker.flush()
        # Shuttle full message logs both ways (a stand-in for the relay).
        b.receive(_drain_messages(a, b), _tree_string(a))
        b.worker.flush()
        a.receive(_drain_messages(b, a), _tree_string(b))
        a.worker.flush()
        ra = a.query_once(q)
        rb = b.query_once(q)
        assert ra == rb and len(ra) == 2
        assert _tree_string(a) == _tree_string(b)
        # LWW: b edits a's row; a receives and sees the newer title.
        b.update("todo", rid, {"title": "edited-by-b"})
        b.worker.flush()
        a.receive(_drain_messages(b, a), _tree_string(b))
        a.worker.flush()
        titles = {r["id"]: r["title"] for r in a.query_once(q)}
        assert titles[rid] == "edited-by-b"
    finally:
        a.dispose()
        b.dispose()


def test_aborted_batch_discards_mutations():
    evolu = make_client()
    try:
        q = table("todo").select("title").serialize()
        with pytest.raises(RuntimeError):
            with evolu.batching():
                evolu.create("todo", {"title": "doomed"})
                raise RuntimeError("abort")
        evolu.create("todo", {"title": "kept"})
        evolu.worker.flush()
        assert [r["title"] for r in evolu.query_once(q)] == ["kept"]
    finally:
        evolu.dispose()


def test_query_once_does_not_leak_subscription():
    evolu = make_client()
    try:
        q = table("todo").select("id").serialize()
        evolu.query_once(q)
        assert q not in evolu._subscribed
        # a later real subscription still gets a fresh initial fetch
        evolu.create("todo", {"title": "x"})
        evolu.worker.flush()
        evolu.subscribe_query(q)
        evolu.worker.flush()
        assert len(evolu.get_query_rows(q)) == 1
    finally:
        evolu.dispose()


def test_send_failure_does_not_push_to_relay():
    """A command that fails after apply must roll back without having
    pushed anything to the transport (push-after-commit discipline)."""
    evolu = make_client()
    try:
        pushed = []
        evolu.worker.post_sync = lambda r: pushed.append(r)
        bad = msg.serialize_query("SELECT broken FROM nowhere")
        evolu.subscribe_query(bad)
        evolu.worker.flush()
        evolu.create("todo", {"title": "x"})  # Send: apply ok, _query raises
        evolu.worker.flush()
        assert pushed == []  # nothing escaped the rolled-back transaction
        rows = evolu.db.exec_sql_query('SELECT COUNT(*) AS n FROM "__message"')
        assert rows[0]["n"] == 0  # local state rolled back consistently
        assert evolu.get_error() is not None
    finally:
        evolu.dispose()


def test_unsubscribe_evicts_caches():
    evolu = make_client()
    try:
        q = table("todo").select("id").serialize()
        unsub = evolu.subscribe_query(q)
        evolu.create("todo", {"title": "x"})
        evolu.worker.flush()
        assert q in evolu.worker.queries_rows_cache
        unsub()
        evolu.worker.flush()
        assert q not in evolu.worker.queries_rows_cache
        assert q not in evolu._rows_cache
    finally:
        evolu.dispose()


def test_offset_without_limit_compiles():
    sql, params = table("todo").offset(3).compile()
    assert "LIMIT -1 OFFSET ?" in sql and params == [3]


def test_schema_without_common_columns_gets_them_appended():
    # Regression: the client must append id/common columns to the DDL the
    # way dbSchemaToTableDefinitions does (db.ts:210-221) — an app schema
    # lists only its own columns.
    evolu = create_evolu({"todo": ("title",)})
    try:
        rid = evolu.create("todo", {"title": "x"})
        evolu.worker.flush()
        rows = evolu.query_once('SELECT "id","title","createdAt","createdBy" FROM "todo"')
        assert rows and rows[0]["id"] == rid and rows[0]["createdAt"]
        evolu.update("todo", rid, {"isDeleted": True})
        evolu.worker.flush()
        rows = evolu.query_once('SELECT "isDeleted","updatedAt" FROM "todo"')
        assert rows[0]["isDeleted"] == 1 and rows[0]["updatedAt"]
    finally:
        evolu.dispose()


def test_queries_accept_raw_sql_and_builders():
    # Regression: subscribe/query_once/get_query_rows accept raw SQL and
    # QueryBuilder objects, not just pre-serialized SqlQueryStrings, and
    # all three key the same cache entry.
    evolu = make_client()
    try:
        evolu.create("todo", {"title": "x"})
        evolu.worker.flush()
        raw = 'SELECT "title" FROM "todo"'
        assert [r["title"] for r in evolu.query_once(raw)] == ["x"]
        builder = table("todo").select("title")
        assert [r["title"] for r in evolu.query_once(builder)] == ["x"]
        unsub = evolu.subscribe_query(raw)
        evolu.worker.flush()
        assert evolu.get_query_rows(raw) == evolu.get_query_rows(builder.serialize())
        unsub()
    finally:
        evolu.dispose()


def test_cross_process_reload_signal(tmp_path):
    import threading

    from evolu_tpu.utils.reload import ReloadWatcher, notify_reload

    db_path = str(tmp_path / "shared.db")
    fired = threading.Event()
    w = ReloadWatcher(db_path, fired.set, interval=0.05)
    try:
        notify_reload(db_path)
        assert fired.wait(2.0), "watcher did not observe the signal"
    finally:
        w.stop()


def test_restore_owner_signals_other_processes(tmp_path):
    import threading

    from evolu_tpu.utils.reload import ReloadWatcher

    db_path = str(tmp_path / "client.db")
    evolu = create_evolu(TODO_SCHEMA, db_path=db_path)
    try:
        fired = threading.Event()
        w = ReloadWatcher(db_path, fired.set, interval=0.05)
        try:
            evolu.restore_owner(evolu.owner.mnemonic)
            evolu.worker.flush()
            assert fired.wait(2.0), "restore_owner did not bump the reload signal"
        finally:
            w.stop()
    finally:
        evolu.dispose()


def test_create_hooks_analog():
    from evolu_tpu.api.hooks import create_hooks

    hooks = create_hooks({"todo": ("title", "isCompleted")})
    try:
        assert not hooks.use_evolu_first_data_are_loaded()
        view = hooks.use_query(lambda t: t("todo").select("title").order_by("createdAt"))
        changes = []
        unsub = view.subscribe(lambda: changes.append(list(view.rows)))
        mutate = hooks.use_mutation()
        mutate("todo", {"title": "a"})
        hooks.evolu.worker.flush()
        assert view.rows == [{"title": "a"}]
        assert hooks.use_evolu_first_data_are_loaded()
        assert changes and changes[-1] == [{"title": "a"}]
        # r9: the subscription's FIRST sweep delivers its (empty)
        # baseline as a root-replace — one initial [] notification
        # (reference useQuery notifies on first load too).
        assert changes[0] == []
        fired = len(changes)
        unsub()
        mutate("todo", {"title": "b"})
        hooks.evolu.worker.flush()
        assert len(view.rows) == 2 and len(changes) == fired  # unsubscribed
        assert hooks.use_owner() is hooks.evolu.owner
        view.dispose()
    finally:
        hooks.evolu.dispose()


def test_joined_reactive_query_drives_query_view():
    """A two-table join as a live subscription: mutations to EITHER
    side re-run the query and notify the view (the reference re-runs
    all subscribed queries after every send/receive, send.ts:121)."""
    from evolu_tpu.api.hooks import create_hooks
    from evolu_tpu.api.query import fn

    schema = {
        "todo": ("title", "isCompleted", "categoryId"),
        "todoCategory": ("name",),
    }
    hooks = create_hooks(schema)
    try:
        mutate = hooks.use_mutation()
        home = mutate("todoCategory", {"name": "home"})
        work = mutate("todoCategory", {"name": "work"})
        mutate("todo", {"title": "dishes", "categoryId": home})
        mutate("todo", {"title": "report", "categoryId": work})
        mutate("todo", {"title": "email", "categoryId": work})

        view = hooks.use_query(
            lambda t: t("todo")
            .select(("todo.title", "title"), ("todoCategory.name", "category"))
            .inner_join("todoCategory", "todoCategory.id", "todo.categoryId")
            .order_by("todo.title")
        )
        counts = hooks.use_query(
            lambda t: t("todo")
            .select("categoryId", fn.count("id").as_("n"))
            .group_by("categoryId")
            .having(fn.count("id"), ">", 1)
        )
        changes = []
        view.subscribe(lambda: changes.append(True))
        hooks.evolu.worker.flush()
        assert view.rows == [
            {"title": "dishes", "category": "home"},
            {"title": "email", "category": "work"},
            {"title": "report", "category": "work"},
        ]
        assert counts.rows == [{"categoryId": work, "n": 2}]

        # Mutating the JOINED side (rename a category) must re-render.
        mutate("todoCategory", {"id": home, "name": "chores"})
        hooks.evolu.worker.flush()
        assert changes
        assert view.rows[0] == {"title": "dishes", "category": "chores"}
        view.dispose(), counts.dispose()
    finally:
        hooks.evolu.dispose()


def test_predicate_trees_drive_query_view():
    """An OR-of-ANDs and a correlated-exists as LIVE subscriptions: the
    compile-only expression tree slots into the reactive runtime with
    zero runtime changes (the reference compiles Kysely expression
    trees the same way, kysely.ts:12-27)."""
    from evolu_tpu.api.hooks import create_hooks
    from evolu_tpu.api.query import and_, c, exists, or_, ref

    schema = {
        "todo": ("title", "isCompleted", "categoryId"),
        "todoCategory": ("name",),
    }
    hooks = create_hooks(schema)
    try:
        mutate = hooks.use_mutation()
        work = mutate("todoCategory", {"name": "work"})
        mutate("todo", {"title": "urgent: ship", "categoryId": None})
        done = mutate("todo", {"title": "rest", "isCompleted": True})
        mutate("todo", {"title": "idle"})

        flagged = hooks.use_query(
            lambda t: t("todo")
            .select("title")
            .where(or_(
                and_(c("isCompleted", "=", 1), c("isDeleted", "is not", 1)),
                c("title", "like", "urgent%"),
            ))
            .order_by("title")
        )
        categorized = hooks.use_query(
            lambda t: t("todo")
            .select("title")
            .where(exists(
                table("todoCategory")
                .select("id")
                .where(c("todoCategory.id", "=", ref("todo.categoryId")))
            ))
            .order_by("title")
        )
        hooks.evolu.worker.flush()
        assert [r["title"] for r in flagged.rows] == ["rest", "urgent: ship"]
        assert categorized.rows == []

        # Mutations re-run both: un-complete one, categorize another.
        changes = []
        flagged.subscribe(lambda: changes.append(True))
        mutate("todo", {"id": done, "isCompleted": False})
        mutate("todo", {"id": done, "categoryId": work})
        hooks.evolu.worker.flush()
        assert changes
        assert [r["title"] for r in flagged.rows] == ["urgent: ship"]
        assert [r["title"] for r in categorized.rows] == ["rest"]
        flagged.dispose(), categorized.dispose()
    finally:
        hooks.evolu.dispose()


def test_model_email_and_url_brands():
    from evolu_tpu.core.types import ValidationError

    assert model.validate_email("user@example.com") == "user@example.com"
    assert model.validate_url("https://example.com/a?b=1") == "https://example.com/a?b=1"
    for bad in ("not-an-email", "a@b", "x y@z.co", "user@example.com\n", None, 123):
        with pytest.raises(ValidationError):
            model.validate_email(bad)
    for bad in ("example.com", "", "http://", "http://[invalid",
                "http://exa mple.com/x", "http://\t.com", None, 5):
        with pytest.raises(ValidationError):
            model.validate_url(bad)


def test_huge_receive_applies_chunked_with_identical_state(tmp_path):
    """A receive batch above receive_chunk_size applies blockwise with
    the clock persisted per chunk; the end state is identical to the
    whole-batch path."""
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.core.merkle import merkle_tree_to_string
    from evolu_tpu.storage.clock import read_clock
    from evolu_tpu.utils.config import Config

    base = 1_700_000_000_000
    messages = tuple(
        CrdtMessage(
            timestamp_to_string(Timestamp(base + i, 0, "b" * 16)),
            "todo", f"r{i % 50}", "title", f"v{i}",
        )
        for i in range(500)
    )
    tree_str = "{}"

    small = create_evolu(TODO_SCHEMA, config=Config(receive_chunk_size=64))
    whole = create_evolu(TODO_SCHEMA, config=Config(receive_chunk_size=None),
                         mnemonic=small.owner.mnemonic)
    try:
        for c in (small, whole):
            c.receive(messages, tree_str, None)
            c.worker.flush()
        dump_a = small.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
        dump_b = whole.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
        assert len(dump_a) == 500 and dump_a == dump_b
        ca, cb = read_clock(small.db), read_clock(whole.db)
        assert merkle_tree_to_string(ca.merkle_tree) == merkle_tree_to_string(cb.merkle_tree)
        # The HLC merged the remote max on both (wall clock/node differ
        # per instance, so only the merged floor is deterministic).
        assert ca.timestamp.millis >= base + 499
        assert cb.timestamp.millis >= base + 499
    finally:
        small.dispose()
        whole.dispose()


def test_huge_receive_mid_failure_keeps_committed_chunks_coherent():
    """With chunked receive, a poisoned later chunk must not roll back
    earlier chunks, and the persisted clock's tree must cover exactly
    the stored messages (digest coherence for resume)."""
    from evolu_tpu.core.merkle import create_initial_merkle_tree, insert_into_merkle_tree, merkle_tree_to_string
    from evolu_tpu.core.timestamp import Timestamp, timestamp_from_string, timestamp_to_string
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.storage.clock import read_clock
    from evolu_tpu.utils.config import Config

    base = 1_700_000_000_000
    good = [
        CrdtMessage(
            timestamp_to_string(Timestamp(base + i, 0, "b" * 16)),
            "todo", f"r{i}", "title", f"v{i}",
        )
        for i in range(100)
    ]
    # Valid timestamp (the HLC fold must pass) but an apply-time failure:
    # the table does not exist, so the LAST chunk's transaction fails.
    poisoned = good + [
        CrdtMessage(
            timestamp_to_string(Timestamp(base + 200, 0, "b" * 16)),
            "no_such_table", "rx", "title", "x",
        )
    ]

    evolu = create_evolu(TODO_SCHEMA, config=Config(receive_chunk_size=40))
    try:
        errors = []
        evolu.subscribe_error(errors.append)
        evolu.receive(tuple(poisoned), "{}", None)
        evolu.worker.flush()
        assert errors, "poisoned batch must surface an error"
        stored = evolu.db.exec('SELECT "timestamp" FROM "__message" ORDER BY "timestamp"')
        # First chunks (2 x 40) committed; the poisoned final chunk rolled back.
        assert len(stored) == 80
        clock = read_clock(evolu.db)
        expect = create_initial_merkle_tree()
        for (ts,) in stored:
            expect = insert_into_merkle_tree(timestamp_from_string(ts), expect)
        assert merkle_tree_to_string(clock.merkle_tree) == merkle_tree_to_string(expect)
    finally:
        evolu.dispose()


def test_huge_receive_mid_failure_still_renders_committed_chunks():
    """OnReceive is staged per committed chunk, so a mid-stream failure
    still re-renders subscribers with the rows earlier chunks committed
    — they must not stay hidden until some later command emits."""
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.utils.config import Config

    base = 1_700_000_000_000
    good = [
        CrdtMessage(
            timestamp_to_string(Timestamp(base + i, 0, "b" * 16)),
            "todo", f"r{i}", "title", f"v{i}",
        )
        for i in range(100)
    ]
    poisoned = good + [
        CrdtMessage(
            timestamp_to_string(Timestamp(base + 200, 0, "b" * 16)),
            "no_such_table", "rx", "title", "x",
        )
    ]

    evolu = create_evolu(TODO_SCHEMA, config=Config(receive_chunk_size=40))
    try:
        q = table("todo").select("title").order_by("title").serialize()
        evolu.subscribe_query(q)
        evolu.worker.flush()
        errors = []
        evolu.subscribe_error(errors.append)
        evolu.receive(tuple(poisoned), "{}", None)
        evolu.worker.flush()
        assert errors, "poisoned batch must surface an error"
        evolu.worker.flush()  # OnReceive posts a follow-up Query command
        # 80 rows committed by the first two chunks are VISIBLE now.
        assert len(evolu.get_query_rows(q)) == 80
    finally:
        evolu.dispose()
