"""Sort-vs-scatter LWW plan bit-identity (ISSUE 4 tentpole).

The dense scatter-argmax plan (ops/scatter_merge.py) must produce
bit-identical host-level results to the r5 sort+scan pipeline wherever
the router admits a batch: masks in batch order, upsert selection,
minute deltas, and the XOR digest — including HLC (counter, node)
tie-breaks, stored-winner equality (the re-XOR quirk), and the
wide/dup fallback routes. The router itself is pinned: duplicate
(cell, k1, k2) batches and over-bound cell ids must keep the sort
path, and EVOLU_MERGE_PLAN must override the config default.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evolu_tpu.ops import to_host_many
from evolu_tpu.ops.merge import (
    _PAD_CELL,
    _plan_full_kernel,
    _plan_full_kernel_scatter,
    plan_merge_sorted_flags,
    unpermute_masks,
)
from evolu_tpu.ops.scatter_merge import (
    MAX_TABLE_BITS,
    batch_has_duplicate_keys,
    merge_plan_path,
    scatter_plan_masks,
    set_plan_path,
    table_size_for,
    use_scatter_plan,
)


@pytest.fixture(autouse=True)
def _reset_plan_path():
    yield
    set_plan_path("auto")


def _random_columns(rng, n, cells, stored=0.6, tie_heavy=False):
    """Adversarial plan columns: heavy cell contention, HLC ties at
    every level (equal millis, equal (millis, counter) resolved by
    node, stored-winner equality), zero-key rows."""
    cell_id = rng.integers(0, cells, n).astype(np.int32)
    if tie_heavy:
        millis = 1_700_000_000_000 + rng.integers(0, 3, n).astype(np.int64)
        counter = rng.integers(0, 2, n).astype(np.int32)
        node = rng.integers(1, 5, n).astype(np.uint64)
    else:
        millis = 1_700_000_000_000 + rng.integers(0, 86_400_000, n).astype(np.int64)
        counter = rng.integers(0, 256, n).astype(np.int32)
        node = rng.integers(1, 2**63, n).astype(np.uint64)
    k1 = (millis.astype(np.uint64) << np.uint64(16)) | counter.astype(np.uint64)
    has = rng.random(cells) < stored
    w_k1 = (
        (1_700_000_000_000 + rng.integers(0, 86_400_000, cells).astype(np.uint64))
        << np.uint64(16)
    ) | rng.integers(0, 256, cells).astype(np.uint64)
    w_k2 = rng.integers(1, 2**63, cells).astype(np.uint64)
    ex_k1 = np.where(has, w_k1, 0)[cell_id].astype(np.uint64)
    ex_k2 = np.where(has, w_k2, 0)[cell_id].astype(np.uint64)
    # Make some rows EQUAL their stored winner (the b-flag re-XOR
    # quirk) — the scatter xor rule's only order-sensitive case.
    dup_of_winner = (rng.random(n) < 0.1) & has[cell_id]
    k1 = np.where(dup_of_winner, ex_k1, k1)
    node = np.where(dup_of_winner, ex_k2, node)
    return cell_id, k1, node, ex_k1, ex_k2


def _dedupe(cell_id, k1, k2, ex_k1, ex_k2):
    """Drop later duplicate (cell, k1, k2) rows so the batch satisfies
    the scatter precondition while keeping the b-row ties."""
    seen = set()
    keep = np.ones(len(cell_id), bool)
    for i, key in enumerate(zip(cell_id.tolist(), k1.tolist(), k2.tolist())):
        if key in seen:
            keep[i] = False
        else:
            seen.add(key)
    return tuple(a[keep] for a in (cell_id, k1, k2, ex_k1, ex_k2))


def _pad(cols, size):
    cell_id, k1, k2, ex_k1, ex_k2 = cols
    n = len(cell_id)
    pad = size - n
    return (
        np.concatenate([cell_id, np.full(pad, int(_PAD_CELL), np.int32)]),
        np.concatenate([k1, np.zeros(pad, np.uint64)]),
        np.concatenate([k2, np.zeros(pad, np.uint64)]),
        np.concatenate([ex_k1, np.zeros(pad, np.uint64)]),
        np.concatenate([ex_k2, np.zeros(pad, np.uint64)]),
    )


def _sort_plan_masks(cols):
    """Oracle: the r5 sorted-flags plan, unpermuted to batch order."""
    xor_s, upsert_s, i_s, _s1, _s2, _ = jax.jit(plan_merge_sorted_flags)(
        *(jnp.asarray(c) for c in cols)
    )
    return unpermute_masks(np.asarray(xor_s), np.asarray(upsert_s), np.asarray(i_s))


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("tie_heavy", [False, True])
def test_scatter_masks_bit_identical_to_sort_plan(seed, tie_heavy):
    rng = np.random.default_rng(seed)
    n = 1536 if tie_heavy else 4000
    cols = _random_columns(rng, n, cells=max(n // 8, 4), tie_heavy=tie_heavy)
    cols = _dedupe(*cols)
    cols = _pad(cols, 4096)
    table = table_size_for(int(cols[0][cols[0] != int(_PAD_CELL)].max()))
    with jax.enable_x64(True):
        xor_o, upsert_o = _sort_plan_masks(cols)
        xor_s, upsert_s = to_host_many(
            *jax.jit(scatter_plan_masks, static_argnames=("table_size",))(
                *(jnp.asarray(c) for c in cols), table_size=table
            )
        )
    np.testing.assert_array_equal(xor_s, xor_o)
    np.testing.assert_array_equal(upsert_s, upsert_o)


def test_scatter_full_kernel_matches_sort_full_kernel():
    """The fused full-plan kernels (masks + minute deltas) agree at the
    host level: batch-order masks, decoded delta dicts."""
    from evolu_tpu.ops.merkle_ops import decode_owner_minute_deltas

    rng = np.random.default_rng(7)
    cols = _dedupe(*_random_columns(rng, 2000, cells=256))
    cols = _pad(cols, 2048)
    table = table_size_for(int(cols[0][cols[0] != int(_PAD_CELL)].max()))
    with jax.enable_x64(True):
        outs_sort = to_host_many(*_plan_full_kernel(*(jnp.asarray(c) for c in cols)))
        outs_scat = to_host_many(
            *_plan_full_kernel_scatter(
                *(jnp.asarray(c) for c in cols), table_size=table
            )
        )
    masks_sort = unpermute_masks(outs_sort[0], outs_sort[1], outs_sort[2])
    masks_scat = unpermute_masks(outs_scat[0], outs_scat[1], outs_scat[2])
    np.testing.assert_array_equal(masks_scat[0], masks_sort[0])
    np.testing.assert_array_equal(masks_scat[1], masks_sort[1])
    size = len(cols[0])
    deltas = [
        decode_owner_minute_deltas(np.zeros(size, np.int32), o[3], o[4], o[5], o[6])
        for o in (outs_sort, outs_scat)
    ]
    assert deltas[0] == deltas[1]


def test_shard_kernel_scatter_matches_packed_kernel_end_to_end():
    """Whole-shard parity on the bench layout: plans, per-owner minute
    deltas, and the digest from `_shard_kernel_scatter` equal the
    packed sort kernel's across an 8-shard mesh."""
    import bench
    from evolu_tpu.ops.merkle_ops import decode_owner_minute_deltas
    from evolu_tpu.parallel.mesh import create_mesh, sharding
    from evolu_tpu.parallel.reconcile import (
        _compiled_kernel,
        _shard_kernel,
        scatter_shard_kernel,
    )

    mesh = create_mesh()
    n_dev = mesh.devices.size
    cols, total = bench.shard_layout(
        bench.build_columns(n=2048, owners=32, stored_winners=True), n_dev
    )
    real = cols["cell_id"] != int(_PAD_CELL)
    table = table_size_for(int(cols["cell_id"].max(initial=0, where=real)))
    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")
    results = {}
    with jax.enable_x64(True):
        for label, kernel in (
            ("sort", _shard_kernel),
            ("scatter", scatter_shard_kernel(table)),
        ):
            args = [jax.device_put(cols[k], shd) for k in names]
            outs = to_host_many(*_compiled_kernel(mesh, kernel)(*args))
            shard_size = total // n_dev
            masks = unpermute_masks(outs[0], outs[1], outs[2], block_size=shard_size)
            deltas = decode_owner_minute_deltas(*outs[3:8])
            results[label] = (masks, deltas, int(outs[8]))
    np.testing.assert_array_equal(results["sort"][0][0], results["scatter"][0][0])
    np.testing.assert_array_equal(results["sort"][0][1], results["scatter"][0][1])
    assert results["sort"][1] == results["scatter"][1]
    assert results["sort"][2] == results["scatter"][2]


def test_router_rejects_duplicates_and_wide_cells():
    cell_id = np.array([1, 2, 1], np.int32)
    k1 = np.array([5, 6, 5], np.uint64)
    k2 = np.array([9, 9, 9], np.uint64)
    assert batch_has_duplicate_keys(cell_id, k1, k2)
    set_plan_path("scatter")
    assert not use_scatter_plan(cell_id, k1, k2)
    # Dup-free passes.
    k1u = np.array([5, 6, 7], np.uint64)
    assert not batch_has_duplicate_keys(cell_id, k1u, k2)
    assert use_scatter_plan(cell_id, k1u, k2)
    # Cell ids beyond the table bound keep the sort path.
    wide = np.array([1 << MAX_TABLE_BITS], np.int32)
    assert not use_scatter_plan(
        wide, np.array([1], np.uint64), np.array([1], np.uint64)
    )


def test_reconcile_router_falls_back_on_duplicate_batch():
    """A batch with an in-batch duplicate key routes to a SORT kernel
    even when scatter is forced — and the shard kernels still produce
    the right plan for it (the dup shape the scatter algebra cannot
    serve)."""
    from evolu_tpu.parallel.reconcile import (
        _shard_kernel,
        _shard_kernel_wide,
        shard_kernel_for,
    )

    cols = {
        "cell_id": np.array([3, 3, 4], np.int32),
        "k1": np.array([5, 5, 6], np.uint64),
        "k2": np.array([9, 9, 9], np.uint64),
        "ex_k1": np.zeros(3, np.uint64),
        "ex_k2": np.zeros(3, np.uint64),
        "owner_ix": np.zeros(3, np.int64),
    }
    set_plan_path("scatter")
    kernel = shard_kernel_for(cols)
    assert kernel in (_shard_kernel, _shard_kernel_wide)
    # The dup-free twin routes to the scatter kernel.
    from evolu_tpu.parallel.reconcile import scatter_shard_kernel

    cols["k1"] = np.array([5, 6, 7], np.uint64)
    assert shard_kernel_for(cols) is scatter_shard_kernel(table_size_for(4))


def test_router_admits_padded_shard_layouts():
    """Padding rows are identical (PAD, 0, 0) triples — the duplicate
    screen must ignore them or every padded mesh layout self-reports
    as duplicate and silently pins the sort path (found by the verify
    drive: fleet reconcile never dispatched scatter)."""
    from evolu_tpu.parallel.reconcile import scatter_shard_kernel, shard_kernel_for

    cols = {
        "cell_id": np.array([3, 4, int(_PAD_CELL), int(_PAD_CELL)], np.int32),
        "k1": np.array([5, 6, 0, 0], np.uint64),
        "k2": np.array([9, 9, 0, 0], np.uint64),
        "ex_k1": np.zeros(4, np.uint64),
        "ex_k2": np.zeros(4, np.uint64),
        "owner_ix": np.zeros(4, np.int64),
    }
    set_plan_path("scatter")
    assert not batch_has_duplicate_keys(cols["cell_id"], cols["k1"], cols["k2"])
    assert shard_kernel_for(cols) is scatter_shard_kernel(table_size_for(4))


def test_env_var_overrides_config(monkeypatch):
    set_plan_path("sort")
    monkeypatch.setenv("EVOLU_MERGE_PLAN", "scatter")
    assert merge_plan_path() == "scatter"
    monkeypatch.setenv("EVOLU_MERGE_PLAN", "sort")
    set_plan_path("scatter")
    assert merge_plan_path() == "sort"
    monkeypatch.setenv("EVOLU_MERGE_PLAN", "scater")  # typo'd pin: loud
    with pytest.raises(ValueError):
        merge_plan_path()
    monkeypatch.delenv("EVOLU_MERGE_PLAN")
    assert merge_plan_path() == "scatter"
    with pytest.raises(ValueError):
        set_plan_path("bogus")


def test_plan_batch_device_full_identical_across_paths():
    """End-to-end through the message planner: PlannedBatch contents
    (xor mask list, upsert identity, deltas) identical under forced
    sort vs forced scatter."""
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.ops.merge import plan_batch_device_full

    rng = np.random.default_rng(11)
    msgs = []
    for i in range(300):
        ts = timestamp_to_string(
            Timestamp(
                millis=1_700_000_000_000 + int(rng.integers(0, 120_000)),
                counter=int(rng.integers(0, 4)),
                node=f"{rng.integers(1, 8):016x}",
            )
        )
        msgs.append(
            CrdtMessage(ts, "t", f"r{int(rng.integers(0, 40))}", "c", i)
        )
    # Dedup identical timestamps per cell (the scatter precondition;
    # duplicates would route to sort and the paths trivially agree).
    seen, unique = set(), []
    for m in msgs:
        key = (m.table, m.row, m.column, m.timestamp)
        if key not in seen:
            seen.add(key)
            unique.append(m)
    winners = {}
    plans = {}
    for path in ("sort", "scatter"):
        set_plan_path(path)
        xor_mask, upserts, deltas = plan_batch_device_full(unique, winners)
        plans[path] = (list(xor_mask), [id(u) for u in upserts], dict(deltas))
    assert plans["sort"] == plans["scatter"]
