"""Continuous-batching sync scheduler (evolu_tpu/server/scheduler.py).

Semantic ground truth: anti-entropy responses depend only on store
state plus the one request (Merkle-CRDTs set reconciliation), so a
fused engine pass over DISTINCT-owner requests must be byte-identical
— wire responses, Merkle tree strings, SQLite end state — to serving
the same requests one-at-a-time. Same-owner requests are ordered: the
scheduler defers the later one to the next batch, and the pair must
come out exactly as a sequential server would produce it.

Robustness: queue-full answers 503 + Retry-After and the client's
bounded backoff recovers without data loss; a poisoned batch is
retried as singletons so one bad request can't fail its batchmates;
stop() drains in-flight work; and varying micro-batch sizes never
recompile the fused jit pipeline (bucket-stable shapes — pinned via
`engine.merkle_jit_cache_size()`, like the bench fence).
"""

import threading
import time
import urllib.error
import urllib.request

import pytest

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    create_initial_merkle_tree,
    merkle_tree_to_string,
    minute_deltas_host,
)
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import metrics
from evolu_tpu.server.relay import RelayServer, RelayStore, ShardedRelayStore
from evolu_tpu.server.scheduler import SchedulerQueueFull, SyncScheduler
from evolu_tpu.sync import protocol

BASE = 1_700_000_000_000
FRESH_NODE = "f" * 16  # no message carries it → own-msg exclusion no-op


def _msgs(node: str, start: int, n: int):
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (start + i) * 1000, 0, node)),
            b"ct-%d" % (start + i),
        )
        for i in range(n)
    )


def _post_raw(url: str, req: protocol.SyncRequest) -> bytes:
    body = protocol.encode_sync_request(req)
    with urllib.request.urlopen(
        urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/octet-stream"}
        ),
        timeout=60,
    ) as r:
        return r.read()


def _run_threads(workers, timeout: float = 120.0):
    barrier = threading.Barrier(len(workers))
    errors = []

    def wrap(fn):
        try:
            barrier.wait(timeout=30)
            fn()
        except Exception as e:  # noqa: BLE001 - collected and re-raised
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(fn,)) for fn in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in threads), "scheduler test thread hung"
    if errors:
        raise errors[0]


def _owner_state(store, user_id: str):
    """(message rows, stored merkle tree string) for one owner."""
    shard = store.shard_of(user_id) if hasattr(store, "shard_of") else store
    rows = shard.db.exec_sql_query(
        'SELECT "timestamp", "content" FROM "message" WHERE "userId" = ? '
        'ORDER BY "timestamp"',
        (user_id,),
    )
    return (
        [(r["timestamp"], r["content"]) for r in rows],
        store.get_merkle_tree_string(user_id),
    )


def test_32_concurrent_mixed_owners_batched_parity_and_fewer_passes():
    """The acceptance shape: 32 concurrent mixed-owner clients through
    the scheduler must produce byte-identical wire responses, Merkle
    tree strings, and SQLite end state as one-at-a-time serving — in
    ≥4× fewer engine passes than per-request dispatch."""
    clients, rounds, per_round = 32, 4, 12
    users = [f"user{i:02d}" for i in range(clients)]
    # Two "devices" per owner: pull legs see the other node's earlier
    # messages, so response byte-identity covers the message stream,
    # not just the tree field.
    nodes = [(f"{2 * i + 1:016x}", f"{2 * i + 2:016x}") for i in range(clients)]
    batches0 = metrics.get_counter("evolu_sched_batches_total")
    coalesced0 = metrics.get_counter("evolu_sched_coalesced_requests_total")

    store = ShardedRelayStore(shards=4)
    server = RelayServer(store, batching=True).start()
    results = {u: [None] * rounds for u in users}
    try:
        def client(u, pair):
            def run():
                for rnd in range(rounds):
                    node = pair[rnd % 2]
                    req = protocol.SyncRequest(
                        _msgs(node, rnd * per_round, per_round), u, node, "{}"
                    )
                    results[u][rnd] = _post_raw(server.url, req)
            return run

        _run_threads([client(u, p) for u, p in zip(users, nodes)])

        oracle = RelayStore()
        try:
            for u, pair in zip(users, nodes):
                for rnd in range(rounds):
                    node = pair[rnd % 2]
                    req = protocol.SyncRequest(
                        _msgs(node, rnd * per_round, per_round), u, node, "{}"
                    )
                    want = oracle.sync_wire(req)
                    if want is None:
                        want = protocol.encode_sync_response(oracle.sync(req))
                    assert results[u][rnd] == want, (u, rnd)
                rows, tree = _owner_state(store, u)
                orows, otree = _owner_state(oracle, u)
                assert rows == orows, u
                assert tree == otree, u
        finally:
            oracle.close()

        n_requests = clients * rounds
        passes = metrics.get_counter("evolu_sched_batches_total") - batches0
        coalesced = (
            metrics.get_counter("evolu_sched_coalesced_requests_total") - coalesced0
        )
        assert coalesced == n_requests, "every request must ride a fused pass"
        assert passes * 4 <= n_requests, (
            f"{n_requests} requests took {passes} engine passes — continuous "
            f"batching must beat per-request dispatch by ≥4×"
        )
    finally:
        server.stop()


def test_duplicate_owner_in_one_batch_keeps_sequential_semantics():
    """Two same-owner requests submitted into ONE coalescing window:
    the second must observe the first's inserts exactly as a
    sequential server would — the scheduler defers it to the next
    pass (2 batches), and both responses + end state are byte-equal
    to sequential serving."""
    store = ShardedRelayStore(shards=2)
    sched = SyncScheduler(store, max_batch=8, max_wait_s=0.3)
    batches0 = metrics.get_counter("evolu_sched_batches_total")
    user = "dup-owner"
    push = protocol.SyncRequest(_msgs("a" * 16, 0, 6), user, "a" * 16, "{}")
    # Cold-sync pull from a second device: sequential-after-push gives
    # it the push's messages; a same-batch merge would too, but a
    # swapped order (pull first) would return an empty stream — the
    # bytes distinguish every wrong interleaving.
    pull = protocol.SyncRequest((), user, FRESH_NODE, "{}")
    got = {}
    try:
        def submit(name, req):
            def run():
                got[name] = sched.submit(req)
            return run

        t1 = threading.Thread(target=submit("push", push))
        t1.start()
        time.sleep(0.05)  # push is queued first, window still open
        t2 = threading.Thread(target=submit("pull", pull))
        t2.start()
        t1.join(30), t2.join(30)
    finally:
        sched.stop()

    oracle = RelayStore()
    try:
        for name, req in (("push", push), ("pull", pull)):
            want = oracle.sync_wire(req)
            if want is None:
                want = protocol.encode_sync_response(oracle.sync(req))
            assert got[name] == want, name
        assert _owner_state(store, user) == _owner_state(oracle, user)
    finally:
        oracle.close()
        store.close()
    assert metrics.get_counter("evolu_sched_batches_total") - batches0 == 2, (
        "same-owner pair must split across exactly two engine passes"
    )
    resp = protocol.decode_sync_response(got["pull"])
    assert [m.timestamp for m in resp.messages] == [
        m.timestamp for m in push.messages
    ], "the deferred pull must see the push's rows"


def test_queue_full_returns_503_with_retry_after():
    store = ShardedRelayStore(shards=2)
    sched = SyncScheduler(store, max_queue=0, retry_after_s=3)
    server = RelayServer(store, scheduler=sched).start()
    rejected0 = metrics.get_counter("evolu_sched_rejected_total")
    try:
        req = protocol.SyncRequest(_msgs("b" * 16, 0, 3), "bp-user", "b" * 16, "{}")
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_raw(server.url, req)
        assert e.value.code == 503
        assert e.value.headers["Retry-After"] == "3"
        assert metrics.get_counter("evolu_sched_rejected_total") == rejected0 + 1
        # Backpressure is flow control: /ping still answers.
        with urllib.request.urlopen(server.url + "/ping", timeout=10) as r:
            assert r.read() == b"ok"
    finally:
        sched.stop()
        server.stop()


def test_backpressure_and_client_backoff_recover_without_data_loss():
    """A deliberately tiny queue in front of a slowed engine: most of 8
    simultaneous clients bounce with 503 + Retry-After, and the sync
    client's bounded backoff (`sync.client._http_post`) retries them
    all through — every message lands exactly once."""
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.sync.client import _http_post

    store = ShardedRelayStore(shards=2)
    eng = BatchReconciler(store)
    orig = eng.run_batch_wire

    def slow_run(reqs):
        time.sleep(0.05)
        return orig(reqs)

    eng.run_batch_wire = slow_run
    sched = SyncScheduler(store, engine=eng, max_batch=8, max_queue=2,
                          retry_after_s=0.02)
    server = RelayServer(store, scheduler=sched).start()
    # Warm the engine's jit pipeline OUTSIDE the contention window: a
    # first-batch compile would stall the tiny queue for seconds and
    # exhaust the clients' bounded retries.
    sched.submit(
        protocol.SyncRequest(_msgs("c" * 16, 0, 4), "bo-warm", "c" * 16, "{}")
    )
    rejected0 = metrics.get_counter("evolu_sched_rejected_total")
    retries0 = metrics.get_counter(
        "evolu_sync_backoff_retries_total", reason="503"
    )
    users = [f"bo{i:02d}" for i in range(8)]
    nodes = [f"{i + 0x10:016x}" for i in range(8)]
    try:
        def client(u, node):
            def run():
                for rnd in range(2):
                    body = protocol.encode_sync_request(
                        protocol.SyncRequest(_msgs(node, rnd * 5, 5), u, node, "{}")
                    )
                    _http_post(server.url, body, retries=30)
            return run

        _run_threads([client(u, n) for u, n in zip(users, nodes)])

        assert metrics.get_counter("evolu_sched_rejected_total") > rejected0, (
            "the tiny queue must actually have bounced someone"
        )
        assert metrics.get_counter(
            "evolu_sync_backoff_retries_total", reason="503"
        ) > retries0, "recovery must have gone through the client backoff"
        for u, node in zip(users, nodes):
            rows, tree = _owner_state(store, u)
            assert [t for t, _c in rows] == [
                m.timestamp for m in _msgs(node, 0, 10)
            ], u
            deltas, _ = minute_deltas_host(t for t, _c in rows)
            assert tree == merkle_tree_to_string(
                apply_prefix_xors(create_initial_merkle_tree(), deltas)
            ), u
    finally:
        sched.stop()
        eng.close()
        server.stop()


def test_poisoned_batch_retried_as_singletons_spares_batchmates():
    from evolu_tpu.server.engine import BatchReconciler

    store = ShardedRelayStore(shards=2)
    eng = BatchReconciler(store)
    orig = eng.run_batch_wire
    state = {"boom": 1}

    def poisoned(reqs):
        if state["boom"]:
            state["boom"] -= 1
            raise RuntimeError("injected device failure")
        return orig(reqs)

    eng.run_batch_wire = poisoned
    sched = SyncScheduler(store, engine=eng, max_batch=8, max_wait_s=0.2)
    poisoned0 = metrics.get_counter("evolu_sched_poisoned_batches_total")
    fb0 = metrics.get_counter("evolu_sched_fallback_total", reason="poison_retry")
    users = [("pz-a", "1" * 16), ("pz-b", "2" * 16), ("pz-c", "3" * 16)]
    got = {}
    try:
        def submit(u, node):
            def run():
                got[u] = sched.submit(
                    protocol.SyncRequest(_msgs(node, 0, 4), u, node, "{}")
                )
            return run

        _run_threads([submit(u, n) for u, n in users])
        assert (
            metrics.get_counter("evolu_sched_poisoned_batches_total")
            == poisoned0 + 1
        )
        assert (
            metrics.get_counter("evolu_sched_fallback_total", reason="poison_retry")
            == fb0 + len(users)
        )
        # The singleton retry produced exactly the per-request bytes,
        # and a later batch rides the engine again (recovery).
        oracle = RelayStore()
        try:
            for u, node in users:
                req = protocol.SyncRequest(_msgs(node, 0, 4), u, node, "{}")
                want = oracle.sync_wire(req) or protocol.encode_sync_response(
                    oracle.sync(req)
                )
                assert got[u] == want, u
        finally:
            oracle.close()
        after = sched.submit(
            protocol.SyncRequest(_msgs("4" * 16, 0, 2), "pz-d", "4" * 16, "{}")
        )
        assert after, "post-poison batches must ride the engine again"
        assert metrics.get_counter(
            "evolu_sched_poisoned_batches_total"
        ) == poisoned0 + 1, "poison must not repeat once the engine recovers"
    finally:
        sched.stop()
        eng.close()
        store.close()


def test_non_canonical_width_prescreens_to_host_path_without_batch_damage():
    """A malformed-width timestamp must never enter a packed batch: it
    dispatches as a singleton on the per-request path (whose host
    oracle is the error surface) and fails ALONE — concurrent
    canonical requests coalesce and succeed."""
    store = ShardedRelayStore(shards=2)
    sched = SyncScheduler(store, max_batch=8, max_wait_s=0.2)
    fb0 = metrics.get_counter("evolu_sched_fallback_total", reason="non_canonical")
    bad = protocol.SyncRequest(
        (protocol.EncryptedCrdtMessage("not-a-timestamp", b"x"),),
        "nc-bad", "9" * 16, "{}",
    )
    ok_req = protocol.SyncRequest(_msgs("8" * 16, 0, 3), "nc-good", "8" * 16, "{}")
    results = {}

    def submit_bad():
        with pytest.raises(Exception):
            sched.submit(bad)
        results["bad"] = "raised"

    def submit_ok():
        results["ok"] = sched.submit(ok_req)

    try:
        _run_threads([submit_bad, submit_ok])
        assert results["bad"] == "raised"
        assert (
            metrics.get_counter("evolu_sched_fallback_total", reason="non_canonical")
            == fb0 + 1
        )
        oracle = RelayStore()
        try:
            want = oracle.sync_wire(ok_req) or protocol.encode_sync_response(
                oracle.sync(ok_req)
            )
            assert results["ok"] == want
        finally:
            oracle.close()
        rows, _t = _owner_state(store, "nc-bad")
        assert rows == [], "the malformed request must have no side effects"
    finally:
        sched.stop()
        store.close()


def test_varying_batch_sizes_never_recompile_the_fused_pipeline():
    """The bench fence, applied to the scheduler: micro-batches of
    different request/row counts inside one power-of-two row bucket
    must keep the engine's jit cache size flat (shapes are padded by
    `ops.bucket_size`; a recompile per batch would wreck serving
    latency)."""
    from evolu_tpu.server import engine as eng_mod

    store = ShardedRelayStore(shards=2)
    sched = SyncScheduler(store, max_batch=8, max_wait_s=0.0)
    try:
        # Warm-up: first pass compiles the bucket's kernel.
        sched.submit(
            protocol.SyncRequest(_msgs("5" * 16, 0, 3), "jit-w", "5" * 16, "{}")
        )
        size0 = eng_mod.merkle_jit_cache_size()
        assert size0 > 0, "warm-up must have compiled the Merkle kernel"
        for i, n in enumerate((1, 5, 17, 33)):  # all ≤ the 64-row bucket
            sched.submit(
                protocol.SyncRequest(
                    _msgs(f"{i + 0x60:016x}", 0, n), f"jit{i}", f"{i + 0x60:016x}", "{}"
                )
            )
        assert eng_mod.merkle_jit_cache_size() == size0, (
            "a varying micro-batch size recompiled the fused pipeline — "
            "shapes must stay bucket-stable"
        )
    finally:
        sched.stop()
        store.close()


def test_stop_drains_inflight_batches():
    """stop() must serve everything already queued (no request dropped
    mid-shutdown) and reject new submits with SchedulerQueueFull."""
    from evolu_tpu.server.engine import BatchReconciler

    store = ShardedRelayStore(shards=2)
    eng = BatchReconciler(store)
    orig = eng.run_batch_wire

    def slow_run(reqs):
        time.sleep(0.08)
        return orig(reqs)

    eng.run_batch_wire = slow_run
    sched = SyncScheduler(store, engine=eng, max_batch=2, max_wait_s=0.0)
    users = [(f"dr{i}", f"{i + 0x30:016x}") for i in range(6)]
    got, errs = {}, []
    try:
        def submit(u, node):
            def run():
                try:
                    got[u] = sched.submit(
                        protocol.SyncRequest(_msgs(node, 0, 3), u, node, "{}")
                    )
                except Exception as e:  # noqa: BLE001
                    errs.append((u, e))
            return run

        threads = [threading.Thread(target=submit(u, n)) for u, n in users]
        for t in threads:
            t.start()
        time.sleep(0.05)  # all enqueued; first slow batch in flight
        sched.stop()  # must drain, not drop
        assert not errs, errs
        for t in threads:
            t.join(30)
        assert all(not t.is_alive() for t in threads)
        for u, node in users:
            assert got[u], u
            rows, _t = _owner_state(store, u)
            assert [t for t, _c in rows] == [m.timestamp for m in _msgs(node, 0, 3)], u
        with pytest.raises(SchedulerQueueFull):
            sched.submit(
                protocol.SyncRequest(_msgs("7" * 16, 0, 1), "late", "7" * 16, "{}")
            )
    finally:
        eng.close()
        store.close()


def test_singleton_fallback_never_overlaps_an_open_engine_pass(monkeypatch):
    """Store writes serialize on the dispatcher thread: a non-batchable
    request arriving mid-pass must be served AFTER the pass, never
    concurrently — `NativeDatabase.transaction()` JOINS an open
    transaction on the shared connection, so a handler-thread fallback
    write acked mid-batch would be silently rolled back if the batch
    later poisoned (review finding)."""
    import evolu_tpu.server.relay as relay_mod
    from evolu_tpu.server.engine import BatchReconciler

    store = ShardedRelayStore(shards=2)
    eng = BatchReconciler(store)
    orig = eng.run_batch_wire
    in_pass = threading.Event()

    def slow(reqs):
        in_pass.set()
        try:
            time.sleep(0.15)
            return orig(reqs)
        finally:
            in_pass.clear()

    eng.run_batch_wire = slow
    orig_serve = relay_mod.serve_single_request
    overlap = []

    def spying_serve(store_, request):
        overlap.append(in_pass.is_set())
        return orig_serve(store_, request)

    monkeypatch.setattr(relay_mod, "serve_single_request", spying_serve)
    sched = SyncScheduler(store, engine=eng, max_batch=4, max_wait_s=0.0)
    bad = protocol.SyncRequest(
        (protocol.EncryptedCrdtMessage("short", b"x"),), "ser-bad", "6" * 16, "{}"
    )
    try:
        t1 = threading.Thread(target=lambda: sched.submit(
            protocol.SyncRequest(_msgs("5" * 16, 0, 2), "ser-ok", "5" * 16, "{}")
        ))
        t1.start()
        in_pass.wait(10)  # the engine pass is genuinely open now

        def submit_bad():
            with pytest.raises(Exception):
                sched.submit(bad)

        t2 = threading.Thread(target=submit_bad)
        t2.start()
        t1.join(30), t2.join(30)
        assert overlap == [False], (
            "the singleton fallback ran while an engine pass (and its "
            "store transactions) were open"
        )
    finally:
        sched.stop()
        eng.close()
        store.close()


# -- client backoff unit surface (sync.client._http_post) --


class _FakeResponse:
    def __init__(self, body: bytes):
        self._body = body

    def read(self):
        return self._body

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def _http_error(code: int, headers: dict):
    import email.message

    msg = email.message.Message()
    for k, v in headers.items():
        msg[k] = v
    return urllib.error.HTTPError("http://x/", code, "err", msg, None)


def test_http_post_backoff_honors_retry_after(monkeypatch):
    from evolu_tpu.sync import client as sync_client

    calls = {"n": 0}

    def fake_urlopen(req, timeout=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise _http_error(503, {"Retry-After": "2"})
        return _FakeResponse(b"pong")

    slept = []
    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    out = sync_client._http_post(
        "http://x/", b"body", sleep=slept.append, rng=lambda: 1.0
    )
    assert out == b"pong"
    assert slept == [2.0], "Retry-After seconds must be honored verbatim"


def test_http_post_backoff_bounded_and_jittered(monkeypatch):
    from evolu_tpu.sync import client as sync_client

    def always_503(req, timeout=None):
        raise _http_error(503, {})

    slept = []
    monkeypatch.setattr(urllib.request, "urlopen", always_503)
    with pytest.raises(urllib.error.HTTPError):
        sync_client._http_post(
            "http://x/", b"body", retries=3, base_delay=0.1,
            sleep=slept.append, rng=lambda: 0.5,
        )
    # Exponential: 0.1, 0.2, 0.4 — halved by the injected jitter draw.
    assert slept == pytest.approx([0.05, 0.1, 0.2])


def test_http_post_retries_connection_errors_then_surfaces(monkeypatch):
    from evolu_tpu.sync import client as sync_client

    calls = {"n": 0}

    def flaky(req, timeout=None):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise urllib.error.URLError(OSError("connection refused"))
        return _FakeResponse(b"ok")

    slept = []
    monkeypatch.setattr(urllib.request, "urlopen", flaky)
    assert sync_client._http_post(
        "http://x/", b"b", sleep=slept.append, rng=lambda: 1.0
    ) == b"ok"
    assert len(slept) == 2

    def dead(req, timeout=None):
        raise urllib.error.URLError(OSError("down"))

    monkeypatch.setattr(urllib.request, "urlopen", dead)
    with pytest.raises(urllib.error.URLError):
        sync_client._http_post(
            "http://x/", b"b", retries=2, sleep=lambda _s: None
        )


def test_http_post_does_not_retry_non_retryable_http(monkeypatch):
    from evolu_tpu.sync import client as sync_client

    calls = {"n": 0}

    def not_found(req, timeout=None):
        calls["n"] += 1
        raise _http_error(404, {})

    monkeypatch.setattr(urllib.request, "urlopen", not_found)
    with pytest.raises(urllib.error.HTTPError):
        sync_client._http_post("http://x/", b"b", sleep=lambda _s: None)
    assert calls["n"] == 1, "4xx other than 429 must surface immediately"
