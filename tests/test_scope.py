"""Partial replication (ISSUE 18): scoped sync filters.

Covers the whole slice pipeline: the client scope model + HMAC lane
tags (sync/scope.py), the ScopeClause wire codec under the
ValueError-only contract with v1 byte-identity when the capability is
absent, relay-side lane tracking with the cardinality cap + overflow
lane, scoped Merkle subtree derivation (device/host fold equivalence,
tree cache coherence), the scoped serve (watermark + lane filtering,
own-node livelock avoidance), push-hub lane gating, the
capability-gated client emission + fleet-failover downgrade
(the PR-8 retarget lesson applied to scope), worker-side deferred
materialization with the counted frontier + typed query deferral +
widen re-materialization, and the scoped snapshot capture.
"""

import random
import urllib.error

import pytest

from evolu_tpu.api import model
from evolu_tpu.api.query import table
from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    diff_merkle_trees,
    merkle_tree_from_string,
    merkle_tree_to_string,
    minute_deltas_host,
)
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import ledger, metrics
from evolu_tpu.runtime import messages as msg
from evolu_tpu.runtime.client import create_evolu
from evolu_tpu.server import scope as server_scope
from evolu_tpu.server.relay import RelayServer, RelayStore, serve_single_request
from evolu_tpu.sync import protocol
from evolu_tpu.sync.client import connect
from evolu_tpu.sync.scope import ScopeDeferred, SyncScope, derive_scope_tag
from evolu_tpu.utils.config import Config

BASE = 1_700_000_000_000
MINUTE = 60_000
NODE_A = "a1b2c3d4e5f60718"
NODE_B = "0f1e2d3c4b5a6978"

SCHEMA = {
    "todo": ("title", "isCompleted", *model.COMMON_COLUMNS),
    "note": ("body", *model.COMMON_COLUMNS),
}


def _ts(millis, counter=0, node=NODE_A):
    return timestamp_to_string(Timestamp(millis, counter, node))


def _emsgs(node, minute, n, start=0):
    return tuple(
        protocol.EncryptedCrdtMessage(
            _ts(BASE + minute * MINUTE + (start + i) * 500, 0, node),
            b"ct-%d-%d" % (minute, start + i),
        )
        for i in range(n)
    )


def _client_tree(timestamps):
    deltas, _ = minute_deltas_host(timestamps)
    return merkle_tree_to_string(apply_prefix_xors({}, deltas))


@pytest.fixture(autouse=True)
def _fresh_scope_state():
    server_scope.tree_cache.reset()
    yield
    server_scope.tree_cache.reset()


# --- scope model + lane tags (sync/scope.py) ---


def test_derive_scope_tag_shape_and_determinism():
    t1 = derive_scope_tag("alpha mnemonic", "todo")
    t2 = derive_scope_tag("alpha mnemonic", "todo")
    assert t1 == t2
    assert len(t1) == 16 and all(c in "0123456789abcdef" for c in t1)
    assert derive_scope_tag("alpha mnemonic", "note") != t1
    assert derive_scope_tag("beta mnemonic", "todo") != t1


def test_sync_scope_model():
    assert SyncScope().is_noop
    s = SyncScope(tables=("todo",))
    assert not s.is_noop
    assert s.table_in_scope("todo")
    assert not s.table_in_scope("note")
    # System tables are always in scope — the substrate stays whole.
    assert s.table_in_scope("__message")
    # No table filter = everything in scope.
    assert SyncScope(watermark_millis=5).table_in_scope("anything")
    with pytest.raises(ValueError):
        SyncScope(watermark_millis=-1)
    with pytest.raises(ValueError):
        SyncScope(tables=tuple(f"t{i}" for i in range(
            protocol._MAX_SCOPE_TAGS + 1)))


def test_widen_semantics():
    s = SyncScope(watermark_millis=100, tables=("todo",))
    w = s.widen(50, ("note",))
    assert w.watermark_millis == 50 and w.tables == ("todo", "note")
    assert s.widen() == s  # no-arg widen is the identity
    with pytest.raises(ValueError):
        s.widen(200)  # raising the watermark narrows
    with pytest.raises(ValueError):
        SyncScope(watermark_millis=100).widen(tables=("todo",))
    # Adding an already-present table is idempotent.
    assert s.widen(tables=("todo",)).tables == ("todo",)


def test_wire_clause():
    assert SyncScope().wire_clause("m") is None
    s = SyncScope(watermark_millis=7, tables=("todo",))
    c = s.wire_clause("m", push_tables=("todo", "note"))
    assert c.watermark_millis == 7
    assert c.tags == (derive_scope_tag("m", "todo"),)
    # Pushed messages are tagged even for OUT-of-scope tables — the
    # relay's lanes must stay truthful for other scoped clients.
    assert c.push_tags == (
        derive_scope_tag("m", "todo"), derive_scope_tag("m", "note"))
    # Watermark-only scope: no lanes requested, no push assignment.
    c2 = SyncScope(watermark_millis=7).wire_clause("m", push_tables=("todo",))
    assert c2.tags == () and c2.push_tags == ()


# --- wire codec (satellite: fuzz + downgrade) ---


def test_scope_clause_roundtrip():
    clause = protocol.ScopeClause(12345, ("aa" * 8, "bb" * 8), ("cc" * 8, ""))
    req = protocol.SyncRequest(
        (protocol.EncryptedCrdtMessage(_ts(BASE), b"x"),
         protocol.EncryptedCrdtMessage(_ts(BASE + 1), b"y")),
        "user1", NODE_A, "{}", ("sync-scope-v1",), clause,
    )
    out = protocol.decode_sync_request(protocol.encode_sync_request(req))
    assert out == req
    assert out.scope.watermark_millis == 12345


def test_unscoped_request_stays_byte_identical():
    """The v1 wire pin: scope=None emits NO field 6 — byte-for-byte
    what the pre-scope encoder produced (the golden protoc fixture in
    test_sync.py pins the same property against reference bytes)."""
    req = protocol.SyncRequest((), "u", NODE_A, "{}")
    base = protocol.encode_sync_request(req)
    assert protocol.encode_request_scope(None) == b""
    assert b"".join((
        protocol._string(2, "u"), protocol._string(3, NODE_A),
        protocol._string(4, "{}"),
    )) == base
    # A no-op scope never reaches the wire (wire_clause → None).
    assert SyncScope().wire_clause("m") is None


def test_scope_decode_bounds():
    too_many = protocol.ScopeClause(
        0, tuple("t%02d" % i for i in range(protocol._MAX_SCOPE_TAGS + 4)))
    with pytest.raises(ValueError):
        protocol.decode_scope_clause(protocol.encode_scope_clause(too_many))
    long_tag = protocol.ScopeClause(0, ("x" * (protocol._MAX_SCOPE_TAG_LEN + 1),))
    with pytest.raises(ValueError):
        protocol.decode_scope_clause(protocol.encode_scope_clause(long_tag))
    # push_tags count must equal the message count.
    bad = protocol.encode_sync_request(
        protocol.SyncRequest((), "u", NODE_A, "{}")
    ) + protocol.encode_request_scope(protocol.ScopeClause(0, (), ("t1",)))
    with pytest.raises(ValueError):
        protocol.decode_sync_request(bad)
    # Negative watermark (10-byte two's-complement varint) rejects.
    neg = protocol._tag(1, 0) + protocol._varint((1 << 64) - 5)
    with pytest.raises(ValueError):
        protocol.decode_scope_clause(neg)
    # Wrong wire type for a tag field rejects.
    with pytest.raises(ValueError):
        protocol.decode_scope_clause(protocol._tag(2, 0) + protocol._varint(7))


def test_scope_codec_fuzz_valueerror_only():
    """Malformed scope bytes — standalone and embedded as field 6 —
    raise ValueError and nothing else (the wire-decoder contract)."""
    rng = random.Random(18)
    prefix = protocol.encode_sync_request(
        protocol.SyncRequest((), "u", NODE_A, "{}"))
    for _ in range(1500):
        blob = rng.randbytes(rng.randrange(0, 80))
        for data in (blob, prefix + protocol._len_delimited(6, blob)):
            try:
                protocol.decode_scope_clause(blob)
            except ValueError:
                pass
            try:
                protocol.decode_sync_request(data)
            except ValueError:
                pass


def test_snapshot_request_scope_roundtrip():
    req = protocol.SnapshotRequest("r1", 4096, ("o1",), BASE, ("aa" * 8,))
    out = protocol.decode_snapshot_request(
        protocol.encode_snapshot_request(req))
    assert out == req
    # Unscoped stays byte-identical (no fields 4/5 emitted).
    plain = protocol.SnapshotRequest("r1", 0, ())
    assert protocol.encode_snapshot_request(plain) == \
        protocol._string(1, "r1")
    with pytest.raises(ValueError):
        protocol.decode_snapshot_request(
            protocol._string(1, "r") + protocol._tag(5, 0) +
            protocol._varint(3))


# --- relay lane tracking + cardinality hardening ---


def test_record_push_lanes_and_overflow_cap():
    store = RelayStore()
    try:
        db = store.db
        before = metrics.get_counter("evolu_scope_overflow_total")
        # Distinct lanes up to the cap record verbatim...
        n = server_scope.MAX_OWNER_LANES
        ts = [_ts(BASE + i) for i in range(n + 10)]
        tags = ["%016x" % i for i in range(n)] + ["%016x" % (n + i) for i in range(10)]
        server_scope.record_push_lanes(db, "u1", ts, tags)
        rows = db.exec_sql_query(
            'SELECT DISTINCT "tag" FROM "scopeLane" WHERE "userId" = ?',
            ("u1",))
        lanes = {r["tag"] for r in rows}
        # ...and the 10 past-cap tags collapsed into the overflow lane.
        assert server_scope.OVERFLOW_TAG in lanes
        assert len(lanes) == server_scope.MAX_OWNER_LANES + 1
        assert metrics.get_counter("evolu_scope_overflow_total") == before + 10
        # Overflow rows are never excluded, whatever lanes a request
        # asks for — the conservative always-served lane.
        excl = server_scope.excluded_timestamps(
            db, "u1", frozenset({"%016x" % 0}))
        assert set(ts[n:]).isdisjoint(excl)
        assert ts[1] in excl  # a known foreign lane IS excludable
        # Untagged pushes ("" per message) record nothing.
        server_scope.record_push_lanes(db, "u2", [_ts(BASE)], [""])
        assert db.exec_sql_query(
            'SELECT * FROM "scopeLane" WHERE "userId" = ?', ("u2",)) == []
    finally:
        store.close()


def test_record_push_lanes_author_only():
    """A resend relays foreign rows; tagging those would let a device
    censor another's rows out of scoped views AND open the
    retroactive-exclusion livelock — with `node_id`, only rows the
    pusher authored get a lane."""
    store = RelayStore()
    try:
        own = _ts(BASE, 0, NODE_A)
        foreign = _ts(BASE + 1, 0, NODE_B)
        server_scope.record_push_lanes(
            store.db, "u1", [own, foreign], ["aa" * 8, "bb" * 8],
            node_id=NODE_A)
        rows = store.db.exec_sql_query(
            'SELECT "timestamp", "tag" FROM "scopeLane" WHERE "userId"=?',
            ("u1",))
        assert {(r["timestamp"], r["tag"]) for r in rows} == {(own, "aa" * 8)}
    finally:
        store.close()


# --- scoped subtree: fold routes + cache ---


def test_scoped_fold_device_host_equivalence(monkeypatch):
    """The masked device minute-fold must equal the host oracle on
    canonical batches; non-canonical hex case must route to the host
    oracle (the r5 contract)."""
    monkeypatch.setattr(server_scope, "SCOPE_DEVICE_FOLD_MIN", 4)
    ts = [_ts(BASE + i * 700, i % 3, NODE_A if i % 2 else NODE_B)
          for i in range(64)]
    mask = [i % 3 != 1 for i in range(64)]
    before_dev = metrics.get_counter("evolu_scope_fold_total", route="device")
    got = server_scope.scoped_minute_deltas(ts, mask)
    assert metrics.get_counter(
        "evolu_scope_fold_total", route="device") == before_dev + 1
    want, _ = minute_deltas_host(t for t, keep in zip(ts, mask) if keep)
    assert got == want
    # Non-canonical case (uppercase node hex): host route, same result.
    bad = [t[:30] + t[30:].upper() for t in ts]
    before_host = metrics.get_counter("evolu_scope_fold_total", route="host")
    got_bad = server_scope.scoped_minute_deltas(bad, mask)
    assert metrics.get_counter(
        "evolu_scope_fold_total", route="host") == before_host + 1
    want_bad, _ = minute_deltas_host(
        t for t, keep in zip(bad, mask) if keep)
    assert got_bad == want_bad


def test_scoped_tree_cache_coherent_by_construction():
    store = RelayStore()
    try:
        store.add_messages("u1", _emsgs(NODE_A, 0, 8))
        clause = protocol.ScopeClause(BASE, (), ())
        full = store.get_merkle_tree_string("u1")
        t1, r1 = server_scope.scoped_tree_for(store, "u1", NODE_B, clause, full)
        hits = metrics.get_counter("evolu_scope_tree_cache_hits_total")
        t2, r2 = server_scope.scoped_tree_for(store, "u1", NODE_B, clause, full)
        assert (t2, r2) == (t1, r1)
        assert metrics.get_counter("evolu_scope_tree_cache_hits_total") == hits + 1
        # Any ingest rewrites the full-tree text → the entry self-invalidates.
        store.add_messages("u1", _emsgs(NODE_A, 1, 4))
        full2 = store.get_merkle_tree_string("u1")
        assert full2 != full
        t3, _r3 = server_scope.scoped_tree_for(store, "u1", NODE_B, clause, full2)
        assert t3 != t1
    finally:
        store.close()


# --- the scoped serve ---


def test_scoped_response_watermark_filter():
    store = RelayStore()
    try:
        old = _emsgs(NODE_A, 0, 6)
        new = _emsgs(NODE_A, 2, 6)
        store.add_messages("u1", old + new)
        wm = BASE + 2 * MINUTE
        req = protocol.SyncRequest(
            (), "u1", NODE_B, "{}", ("sync-scope-v1",),
            protocol.ScopeClause(wm, (), ()))
        resp = server_scope.scoped_response(store, req)
        got = [m.timestamp for m in resp.messages]
        assert got == [m.timestamp for m in new]
        # The scoped tree covers exactly the slice.
        assert resp.merkle_tree == _client_tree(got)
        # An unscoped request still serves everything (full tree).
        full = store.sync(protocol.SyncRequest((), "u1", NODE_B, "{}"))
        assert len(full.messages) == 12
        # Convergence within the slice: a client holding the slice
        # diffs to None — served nothing, no livelock.
        req2 = protocol.SyncRequest(
            (), "u1", NODE_B, resp.merkle_tree, ("sync-scope-v1",),
            protocol.ScopeClause(wm, (), ()))
        resp2 = server_scope.scoped_response(store, req2)
        assert resp2.messages == ()
    finally:
        store.close()


def test_scoped_response_lane_filter_and_unknown_conservative():
    store = RelayStore()
    try:
        tag_todo = derive_scope_tag("m", "todo")
        tag_note = derive_scope_tag("m", "note")
        todo_rows = _emsgs(NODE_A, 0, 4)
        note_rows = _emsgs(NODE_A, 1, 4)
        untagged = _emsgs(NODE_A, 2, 3)
        # A pushes with lane assignments for the first two batches.
        push = protocol.SyncRequest(
            todo_rows + note_rows, "u1", NODE_A, "{}", ("sync-scope-v1",),
            protocol.ScopeClause(0, (tag_todo,),
                                 (tag_todo,) * 4 + (tag_note,) * 4))
        serve_single_request(store, push)
        # ...and a v1 device pushes rows with no lane attribution.
        serve_single_request(
            store, protocol.SyncRequest(untagged, "u1", NODE_A, "{}"))
        # B pulls the todo lane only: known-note rows withheld, the
        # unknown-lane rows served conservatively.
        pull = protocol.SyncRequest(
            (), "u1", NODE_B, "{}", ("sync-scope-v1",),
            protocol.ScopeClause(0, (tag_todo,), ()))
        resp = server_scope.scoped_response(store, pull)
        got = {m.timestamp for m in resp.messages}
        assert got == {m.timestamp for m in todo_rows + untagged}
        assert resp.merkle_tree == _client_tree(sorted(got))
        # Slice convergence: holding the slice → nothing more.
        again = protocol.SyncRequest(
            (), "u1", NODE_B, resp.merkle_tree, ("sync-scope-v1",),
            protocol.ScopeClause(0, (tag_todo,), ()))
        assert server_scope.scoped_response(store, again).messages == ()
    finally:
        store.close()


def test_scoped_serve_own_rows_no_livelock():
    """The membership rule's own-node arm: a client whose OWN writes
    fall outside its scope must not livelock — its rows stay in the
    scoped tree (XOR-cancel against its local copies) while the
    response excludes them as always."""
    store = RelayStore()
    try:
        tag_todo = derive_scope_tag("m", "todo")
        tag_note = derive_scope_tag("m", "note")
        own_note = _emsgs(NODE_B, 0, 5)  # B's own out-of-scope rows
        push = protocol.SyncRequest(
            own_note, "u1", NODE_B, "{}", ("sync-scope-v1",),
            protocol.ScopeClause(0, (tag_todo,), (tag_note,) * 5))
        serve_single_request(store, push)
        # B's local tree holds its own rows; the scoped serve's tree
        # must equal it exactly → diff None, empty response, no loop.
        local = _client_tree([m.timestamp for m in own_note])
        pull = protocol.SyncRequest(
            (), "u1", NODE_B, local, ("sync-scope-v1",),
            protocol.ScopeClause(0, (tag_todo,), ()))
        resp = server_scope.scoped_response(store, pull)
        assert resp.messages == ()
        assert diff_merkle_trees(
            merkle_tree_from_string(resp.merkle_tree),
            merkle_tree_from_string(local)) is None
    finally:
        store.close()


def test_serve_single_request_scoped_ledger_clean():
    """A scoped serve is egress classification, not flow: the
    conservation ledger must stay balanced (`audit() == []`)."""
    ledger.reset()
    store = RelayStore()
    try:
        push = protocol.SyncRequest(
            _emsgs(NODE_A, 0, 10), "u1", NODE_A, "{}", ("sync-scope-v1",),
            protocol.ScopeClause(0, (derive_scope_tag("m", "todo"),),
                                 (derive_scope_tag("m", "todo"),) * 10))
        # The HTTP handler tallies ingress at its decode boundary;
        # calling the serve recipe directly, we mirror that here.
        ledger.count(ledger.INGRESS_SYNC, len(push.messages), owner="u1")
        serve_single_request(store, push)
        pull = protocol.SyncRequest(
            (), "u1", NODE_B, "{}", ("sync-scope-v1",),
            protocol.ScopeClause(BASE, (), ()))
        out = protocol.decode_sync_response(serve_single_request(store, pull))
        assert len(out.messages) == 10
        assert ledger.audit() == []
        stations = ledger.snapshot()["stations"]
        assert stations.get(ledger.SERVE_SCOPED, 0) == 10
    finally:
        store.close()
        ledger.reset()


# --- push hub lane gating ---


def test_event_wakes_truth_table():
    from evolu_tpu.server.push import _event_wakes

    fs = frozenset
    # Own-write exclusion unchanged.
    assert not _event_wakes(fs({NODE_A}), None, NODE_A, None)
    assert _event_wakes(fs({NODE_B}), None, NODE_A, None)
    # Both sides known and disjoint → skip; overlapping → wake.
    assert not _event_wakes(fs({NODE_B}), fs({"t1"}), NODE_A, fs({"t2"}))
    assert _event_wakes(fs({NODE_B}), fs({"t1", "t2"}), NODE_A, fs({"t2"}))
    # Either side unknown → conservative wake.
    assert _event_wakes(fs({NODE_B}), None, NODE_A, fs({"t2"}))
    assert _event_wakes(fs({NODE_B}), fs({"t1"}), NODE_A, None)
    assert _event_wakes(None, None, NODE_A, fs({"t2"}))
    # The gates are independent: unknown authorship doesn't bypass a
    # known-disjoint lane gate.
    assert not _event_wakes(None, fs({"t1"}), NODE_A, fs({"t2"}))


def test_parse_poll_query_tags():
    from evolu_tpu.server.push import parse_poll_query

    owner, node, cursor, timeout, tags = parse_poll_query(
        f"owner=u1&node={NODE_A}&cursor=0&tags=aa,bb")
    assert tags == frozenset({"aa", "bb"})
    assert parse_poll_query(f"owner=u1&node={NODE_A}&cursor=0")[4] is None
    with pytest.raises(ValueError):
        parse_poll_query(
            f"owner=u1&node={NODE_A}&cursor=0&tags="
            + ",".join("t%d" % i for i in range(protocol._MAX_SCOPE_TAGS + 1)))
    with pytest.raises(ValueError):
        parse_poll_query(
            f"owner=u1&node={NODE_A}&cursor=0&tags="
            + "x" * (protocol._MAX_SCOPE_TAG_LEN + 1))


def test_hub_lane_gated_wakeups():
    from evolu_tpu.server.push import PushHub

    hub = PushHub()
    try:
        # Prime the channel so cursors have a floor.
        hub.notify("u1", [_ts(BASE, 0, NODE_B)], tags=None)
        cursor = 1
        kind, val = hub.park("u1", NODE_A, cursor + 0, None, token="tok1",
                             tags=frozenset({"t1"}))
        # The mint event has unknown tags → immediate wake is possible;
        # park from the current seq instead.
        if kind == "now":
            kind, val = hub.park("u1", NODE_A, 2, None, token="tok1",
                                 tags=frozenset({"t1"}))
        assert kind == "parked"
        # A foreign write in a DIFFERENT lane must not wake.
        woken = hub.notify("u1", [_ts(BASE + 1, 0, NODE_B)],
                           tags=frozenset({"t2"}))
        assert woken == 0
        # Same lane → wakes.
        woken = hub.notify("u1", [_ts(BASE + 2, 0, NODE_B)],
                           tags=frozenset({"t1"}))
        assert woken == 1
        # Unknown event tags → conservative wake for a scoped waiter.
        kind, _ = hub.park("u1", NODE_A, 3, None, token="tok2",
                           tags=frozenset({"t1"}))
        assert kind == "parked"
        assert hub.notify("u1", [_ts(BASE + 3, 0, NODE_B)], tags=None) == 1
    finally:
        hub.close()


# --- client emission gate + failover downgrade (satellite) ---


def test_scope_clause_capability_gated_end_to_end():
    """Round 1 (nothing negotiated): no clause on the wire — no lane
    state at the relay. Round 2 (echo landed): the clause rides and
    lanes record."""
    server = RelayServer().start()
    try:
        cfg = Config(sync_url=server.url,
                     sync_scope=SyncScope(tables=("todo",)))
        ev = create_evolu(SCHEMA, config=cfg)
        tr = connect(ev)
        try:
            def round_trip():
                ev.worker.flush(); tr.flush(); ev.worker.flush()

            ev.create("todo", {"title": "r1"})
            round_trip()
            assert protocol.CAP_SYNC_SCOPE in \
                tr.negotiated_capabilities[server.url]
            # Round 1 was unnegotiated: the push carried no clause.
            assert server.store.db.exec_sql_query(
                "SELECT name FROM sqlite_schema WHERE name='scopeLane'") == []
            ev.create("todo", {"title": "r2"})
            round_trip()
            rows = server.store.db.exec_sql_query(
                'SELECT "tag" FROM "scopeLane"')
            assert {r["tag"] for r in rows} == {
                derive_scope_tag(ev.owner.mnemonic, "todo")}
            assert ev.get_error() is None
        finally:
            ev.dispose()
    finally:
        server.stop()


def test_scope_failover_reencodes_without_clause():
    """The PR-8 retarget lesson: a failover target that never
    advertised sync-scope-v1 must never receive a scope clause."""
    from evolu_tpu.utils.config import FleetConfig

    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = RelayServer(RelayStore(), capabilities=(), peers=[],
                    replication_interval_s=30).start()
    cfg = FleetConfig(relays=(a.url, b.url), replication_factor=2, version=1)
    a.enable_fleet(cfg)
    b.enable_fleet(cfg)
    ev = None
    try:
        ev = create_evolu(SCHEMA, config=Config(
            sync_url=b.url, sync_scope=SyncScope(tables=("todo",))))
        tr = connect(ev)
        tr._routes[ev.owner.id] = a.url + "/"

        def round_trip():
            ev.worker.flush(); tr.flush(); ev.worker.flush()

        ev.create("todo", {"title": "r1"})
        round_trip()
        assert protocol.CAP_SYNC_SCOPE in \
            tr.negotiated_capabilities[a.url + "/"]
        ev.create("todo", {"title": "r2"})
        round_trip()
        assert a.store.db.exec_sql_query(
            "SELECT name FROM sqlite_schema WHERE name='scopeLane'")
        # A dies; the round fails over to B, which never advertised —
        # the clause must be dropped in the re-encode.
        a.stop()
        errors = []
        ev.subscribe_error(errors.append)
        before = metrics.get_counter("evolu_scope_downgrades_total",
                                     reason="failover")
        ev.create("todo", {"title": "r3"})
        round_trip()
        assert not errors
        assert metrics.get_counter(
            "evolu_scope_downgrades_total", reason="failover") == before + 1
        assert b.store.user_ids() == [ev.owner.id]
        assert b.store.db.exec_sql_query(
            "SELECT name FROM sqlite_schema WHERE name='scopeLane'") == []
    finally:
        if ev is not None:
            ev.dispose()
        b.stop()


def test_unadvertising_relay_strips_hostile_clause():
    """A relay with the capability OFF answers a scoped request with
    the full serve (over-approximation), never an error."""
    server = RelayServer(RelayStore(), capabilities=()).start()
    try:
        from evolu_tpu.sync.client import _http_post

        serve_single_request(server.store,
                             protocol.SyncRequest(_emsgs(NODE_A, 0, 4),
                                                  "u1", NODE_A, "{}"))
        body = protocol.encode_sync_request(protocol.SyncRequest(
            (), "u1", NODE_B, "{}", ("sync-scope-v1",),
            protocol.ScopeClause(BASE + MINUTE, (), ())))
        out = protocol.decode_sync_response(_http_post(server.url, body))
        assert len(out.messages) == 4  # full serve, watermark ignored
    finally:
        server.stop()


# --- worker: deferred materialization + typed deferral + widen ---


def _drain(src, dst):
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.storage.clock import read_clock

    node = read_clock(dst.db).timestamp.node
    rows = src.db.exec_sql_query(
        'SELECT * FROM "__message" WHERE "timestamp" NOT LIKE \'%\' || ? '
        'ORDER BY "timestamp"', (node,))
    return tuple(
        CrdtMessage(r["timestamp"], r["table"], r["row"], r["column"],
                    r["value"]) for r in rows)


def _tree_str(ev):
    from evolu_tpu.storage.clock import read_clock

    return merkle_tree_to_string(read_clock(ev.db).merkle_tree)


def test_worker_defers_out_of_scope_then_widens():
    full = create_evolu(SCHEMA)
    thin = create_evolu(
        SCHEMA, config=Config(sync_scope=SyncScope(tables=("todo",))))
    try:
        full.create("todo", {"title": "t1"})
        full.create("note", {"body": "n1"})
        full.create("note", {"body": "n2"})
        full.worker.flush()
        drained = _drain(full, thin)
        n_note = sum(1 for m in drained if m.table == "note")
        thin.receive(drained, _tree_str(full))
        thin.worker.flush()
        q_todo = table("todo").select("title").serialize()
        q_note = table("note").select("body").serialize()
        assert [r["title"] for r in thin.query_once(q_todo)] == ["t1"]
        # The out-of-scope table has NO materialized rows...
        assert thin.db.exec_sql_query('SELECT * FROM "note"') == []
        # ...but its messages are in the log and the tree: the thin
        # replica is byte-identical to the full one at the substrate.
        assert _tree_str(thin) == _tree_str(full)
        # The deferral is counted, never silent.
        frontier = thin.db.exec_sql_query(
            'SELECT "table", "rows" FROM "__scope_deferred"')
        assert {(r["table"], r["rows"]) for r in frontier} == {("note", n_note)}
        # A query against the deferred table answers a TYPED marker.
        thin.query_once(q_note)
        thin.worker.flush()
        err = thin.get_error()
        assert isinstance(err, ScopeDeferred)
        assert err.tables == ("note",) and err.deferred_rows == n_note
        # Widen to full: re-materializes from the local log in LWW
        # order and clears the frontier.
        thin.worker.post(msg.WidenSyncScope(full=True))
        thin.worker.flush()
        assert thin.worker.config.sync_scope is None
        assert [r["body"] for r in sorted(
            thin.db.exec_sql_query('SELECT "body" FROM "note"'),
            key=lambda r: r["body"])] == ["n1", "n2"]
        assert thin.db.exec_sql_query(
            'SELECT * FROM "__scope_deferred"') == []
        # And the re-materialized rows answer queries normally.
        bodies = sorted(r["body"] for r in thin.query_once(q_note))
        assert bodies == ["n1", "n2"]
        assert _tree_str(thin) == _tree_str(full)
    finally:
        full.dispose()
        thin.dispose()


def test_worker_widen_rematerializes_lww_winner():
    """Conflicting edits inside the deferred window: the widen replay
    must land the LWW winner, byte-identical to an unscoped apply."""
    full = create_evolu(SCHEMA)
    thin = create_evolu(
        SCHEMA, config=Config(sync_scope=SyncScope(tables=("todo",))))
    try:
        rid = full.create("note", {"body": "v1"})
        full.worker.flush()
        full.update("note", rid, {"body": "v2"})
        full.worker.flush()
        thin.receive(_drain(full, thin), _tree_str(full))
        thin.worker.flush()
        assert thin.db.exec_sql_query('SELECT * FROM "note"') == []
        thin.worker.post(msg.WidenSyncScope(full=True))
        thin.worker.flush()
        rows = thin.db.exec_sql_query('SELECT "id", "body" FROM "note"')
        assert [(r["id"], r["body"]) for r in rows] == [(rid, "v2")]
    finally:
        full.dispose()
        thin.dispose()


def test_worker_widen_narrowing_surfaces_error():
    thin = create_evolu(
        SCHEMA, config=Config(sync_scope=SyncScope(
            watermark_millis=100, tables=("todo",))))
    try:
        thin.worker.post(msg.WidenSyncScope(watermark_millis=200))
        thin.worker.flush()
        assert isinstance(thin.get_error(), ValueError)
        # The scope is untouched after the failed command.
        assert thin.worker.config.sync_scope.watermark_millis == 100
    finally:
        thin.dispose()


def test_scoped_clients_converge_within_slice_through_relay():
    """End-to-end through a live relay: a full and a thin device of
    one owner; the thin device converges byte-identically WITHIN its
    slice and defers the rest with an exact counter."""
    server = RelayServer().start()
    try:
        full = create_evolu(SCHEMA, config=Config(sync_url=server.url))
        thin = create_evolu(
            SCHEMA, mnemonic=full.owner.mnemonic,
            config=Config(sync_url=server.url,
                          sync_scope=SyncScope(tables=("todo",))))
        tf, tt = connect(full), connect(thin)
        try:
            q = table("todo").select("title").order_by("title").serialize()
            full.create("todo", {"title": "a"})
            full.create("note", {"body": "hidden"})
            thin.create("todo", {"title": "b"})
            for _ in range(6):
                full.worker.flush(); tf.flush(); full.worker.flush()
                thin.worker.flush(); tt.flush(); thin.worker.flush()
                full.sync(refresh_queries=False)
                thin.sync(refresh_queries=False)
            assert [r["title"] for r in full.query_once(q)] == ["a", "b"]
            assert [r["title"] for r in thin.query_once(q)] == ["a", "b"]
            assert full.get_error() is None
            # The slice boundary: thin materialized no note rows. (The
            # relay serves them conservatively — full's pushes carry
            # lane tags only once ITS scope clause would; full has no
            # scope, so note rows ride in unknown lanes — and the
            # worker's filter defers them client-side, counted.)
            assert thin.db.exec_sql_query('SELECT * FROM "note"') == []
            front = thin.db.exec_sql_query(
                'SELECT "rows" FROM "__scope_deferred" WHERE "table"=?',
                ("note",))
            assert front and front[0]["rows"] > 0
        finally:
            full.dispose()
            thin.dispose()
    finally:
        server.stop()


# --- scoped snapshot capture ---


def test_scoped_snapshot_capture_regenerates_trees():
    from evolu_tpu.server import snapshot

    store = RelayStore()
    try:
        old = _emsgs(NODE_A, 0, 10)
        new = _emsgs(NODE_A, 3, 10)
        store.add_messages("u1", old + new)
        wm = BASE + 3 * MINUTE
        manifest, chunks = snapshot.capture_snapshot(
            store, watermark_millis=wm)
        recs = [r for c in chunks for r in snapshot.iter_records(c)]
        kept = [r[1] for r in recs if r[0] == "M"]
        assert kept == [m.timestamp for m in new]
        # The shipped tree is recomputed from the kept rows — the
        # installer's recompute-from-rows verify passes unchanged.
        trees = {r[1]: r[2] for r in recs if r[0] == "T"}
        assert trees["u1"] == _client_tree(kept)
        dest = RelayStore()
        try:
            snapshot.install_stream(dest, manifest, chunks)
            assert dest.get_merkle_tree_string("u1") == _client_tree(kept)
        finally:
            dest.close()
        # Unscoped capture is untouched (no scope filter applied).
        m2, c2 = snapshot.capture_snapshot(store)
        recs2 = [r for c in c2 for r in snapshot.iter_records(c)]
        assert sum(1 for r in recs2 if r[0] == "M") == 20
    finally:
        store.close()
