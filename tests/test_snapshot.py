"""Snapshot checkpoint & peer bootstrap (server/snapshot.py).

No reference equivalent — the reference relay is a single node that
never cold-starts. These tests pin the subsystem's contracts: the
snapshot wire codec (ValueError only), native-vs-stdlib capture parity
(byte-identical framing), record-aligned crc-checked chunking, the
acceptance scenario — a fresh relay bootstrapping from a donor holding
≥100 owners / ≥10k messages converges BYTE-identically (trees and
tables) in ≥5× fewer HTTP round-trips than pure PR-3 anti-entropy
(counter-asserted) — the golden-parity verify gate (corrupted chunks
and tampered trees abort with live tables untouched), lagging-peer
local-row merge through the XOR gate, watermark handoff to normal
gossip, in-process fetch-interruption resume, SIGKILL-between-chunks
process crash resume without re-transferring completed chunks, and
atomic local checkpoints (write/restore/corruption)."""

import os
import select
import signal
import subprocess
import sys
import time
import urllib.error
import zlib

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import metrics
from evolu_tpu.server import snapshot
from evolu_tpu.server.relay import RelayServer, RelayStore, ShardedRelayStore
from evolu_tpu.server.replicate import ReplicationManager
from evolu_tpu.sync import protocol
from evolu_tpu.sync.client import _http_post

BASE = 1_700_000_000_000
MINUTE = 60_000
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _msgs(node, minute, start, n, payload=b""):
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(
                Timestamp(BASE + minute * MINUTE + (start + i) * 500, 0, node)
            ),
            b"ct\x00-%d-%d" % (minute, start + i) + payload,
        )
        for i in range(n)
    )


def _fast_post(url, body):
    return _http_post(url, body, retries=0)


def _state(store):
    """Byte-level replica state: per owner, the STORED tree text and
    every message row — what must be identical after a bootstrap."""
    return {
        u: (store.get_merkle_tree_string(u), store.replica_messages(u, ""))
        for u in sorted(store.user_ids())
    }


def _seed(store, owners, per_minute, minutes, payload=b""):
    for i in range(owners):
        node = f"{i + 1:016x}"
        for m in range(minutes):
            store.add_messages(
                f"owner{i:03d}", _msgs(node, m, 0, per_minute, payload)
            )


def _round_trips(replica_id):
    return sum(
        metrics.get_counter("evolu_repl_round_trips_total",
                            replica=replica_id, leg=leg)
        for leg in ("summary", "pull", "snapshot", "snapshot/chunk")
    )


# -- wire codec --


def _codec_vectors():
    manifest = protocol.SnapshotManifest(
        "snap-1", (100, 7), (0xDEADBEEF, 0), (("alice", -123456, 42),
                                              ("b\x00ob", 0, 0xFFFFFFFF)),
        12345, 107,
    )
    req = protocol.SnapshotRequest("replica-9", 1 << 20)
    creq = protocol.SnapshotChunkRequest("snap-1", 3, "replica-9")
    chunk = protocol.SnapshotChunk("snap-1", 3, 0xCAFEBABE, b"\x00\xffpayload")
    return manifest, req, creq, chunk


def test_snapshot_wire_codec_round_trips():
    manifest, req, creq, chunk = _codec_vectors()
    assert protocol.decode_snapshot_manifest(
        protocol.encode_snapshot_manifest(manifest)) == manifest
    assert protocol.decode_snapshot_request(
        protocol.encode_snapshot_request(req)) == req
    assert protocol.decode_snapshot_chunk_request(
        protocol.encode_snapshot_chunk_request(creq)) == creq
    assert protocol.decode_snapshot_chunk(
        protocol.encode_snapshot_chunk(chunk)) == chunk


def test_snapshot_wire_decoders_raise_valueerror_only():
    """The wire-decoder invariant applies to the snapshot codec: ANY
    malformed input raises ValueError only."""
    import random

    manifest, req, creq, chunk = _codec_vectors()
    valid = [
        protocol.encode_snapshot_manifest(manifest),
        protocol.encode_snapshot_request(req),
        protocol.encode_snapshot_chunk_request(creq),
        protocol.encode_snapshot_chunk(chunk),
    ]
    rng = random.Random(11)
    cases = [b"\xff", b"\x08", b"\x0a\x05ab", b"\x08\x01",
             b"\x0d\x01\x02\x03\x04", b"\x22\x02\x08\x01"]
    for blob in valid:
        cases.extend(blob[:k] for k in range(1, len(blob), 5))
        for _ in range(40):
            b = bytearray(blob)
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            cases.append(bytes(b))
        cases.extend(bytes(rng.randrange(256) for _ in range(n)) for n in (3, 17, 64))
    decoders = (
        protocol.decode_snapshot_manifest,
        protocol.decode_snapshot_request,
        protocol.decode_snapshot_chunk_request,
        protocol.decode_snapshot_chunk,
    )
    for dec in decoders:
        for data in cases:
            try:
                dec(bytes(data))
            except ValueError:
                pass  # the ONLY sanctioned error type


# -- capture + framing --


def test_capture_native_matches_python_oracle():
    """The one-C-call capture leg frames byte-identically to the
    stdlib SQL oracle — including NUL-bearing contents and multiple
    owners across minutes."""
    from evolu_tpu.storage.native import native_available

    if not native_available():
        pytest.skip("native host library unavailable")
    nat, py = RelayStore(backend="native"), RelayStore(backend="python")
    for s in (nat, py):
        _seed(s, owners=5, per_minute=9, minutes=3)
    with nat.db.transaction():
        raw_native = snapshot.capture_shard(nat.db)
        raw_oracle = snapshot._capture_shard_py(nat.db)
    with py.db.transaction():
        raw_py = snapshot.capture_shard(py.db)
    assert raw_native == raw_oracle == raw_py
    nat.close()
    py.close()


def test_chunks_split_at_record_boundaries_and_reassemble():
    store = RelayStore()
    _seed(store, owners=4, per_minute=20, minutes=2)
    manifest, chunks = snapshot.capture_snapshot(store, chunk_bytes=300)
    assert len(chunks) > 3
    assert b"".join(chunks) == b"".join(chunks)  # sanity
    for c, size, crc in zip(chunks, manifest.chunk_sizes, manifest.chunk_crcs):
        assert len(c) == size
        assert zlib.crc32(c) == crc
        list(snapshot.iter_records(c))  # every chunk parses standalone
    recs = [r for c in chunks for r in snapshot.iter_records(c)]
    assert sum(1 for r in recs if r[0] == "M") == manifest.message_count == 160
    assert sum(1 for r in recs if r[0] == "T") == len(manifest.owners) == 4
    store.close()


# -- the acceptance scenario --


def test_fresh_peer_bootstrap_beats_anti_entropy_5x_in_round_trips():
    """A fresh relay bootstrapping from a donor holding 128 owners /
    12,288 messages converges byte-identically (trees AND tables), in
    ≥5× fewer HTTP round-trips than pure PR-3 anti-entropy under the
    donor's configured serve_pull caps (constructor args — satellite).
    Round-trips are counter-asserted on the puller's transport leg
    counter, byte-identity on full stored state."""
    donor_store = ShardedRelayStore(shards=2)
    _seed(donor_store, owners=128, per_minute=12, minutes=8)
    donor_mgr = ReplicationManager(
        donor_store, [], replica_id="accept-donor",
        pull_messages_per_owner=64, pull_messages_per_response=512,
    )
    donor = RelayServer(donor_store, replication=donor_mgr).start()
    try:
        donor_state = _state(donor_store)
        assert len(donor_state) == 128
        assert sum(len(rows) for _t, rows in donor_state.values()) == 12288

        # Leg A: pure anti-entropy (bootstrap disabled — the PR-3 path).
        dest_a = RelayStore()
        mgr_a = ReplicationManager(
            dest_a, [donor.url], replica_id="accept-anti", http_post=_fast_post,
        )
        for _ in range(200):
            mgr_a.run_once()
            if _state(dest_a) == donor_state:
                break
        assert _state(dest_a) == donor_state, "anti-entropy never converged"
        anti_rts = _round_trips("accept-anti")

        # Leg B: snapshot bootstrap.
        dest_b = RelayStore()
        mgr_b = ReplicationManager(
            dest_b, [donor.url], replica_id="accept-snap", http_post=_fast_post,
            bootstrap_lag_owners=8, snapshot_chunk_bytes=512 * 1024,
        )
        mgr_b.run_once()  # bootstrap
        mgr_b.run_once()  # post-watermark gossip round (verifies converged)
        assert _state(dest_b) == donor_state, "bootstrap state diverged"
        snap_rts = _round_trips("accept-snap")

        assert snap_rts * 5 <= anti_rts, (snap_rts, anti_rts)
        # The snapshot leg moved ZERO ranged-pull messages — the whole
        # history rode the chunk stream.
        assert metrics.get_counter(
            "evolu_repl_messages_pulled_total",
            replica="accept-snap", peer=donor.url,
        ) == 0
        assert metrics.get_counter(
            "evolu_snap_installs_total", result="ok",
            replica="accept-snap", peer=donor.url,
        ) == 1
        mgr_a.stop()
        mgr_b.stop()
        dest_a.close()
        dest_b.close()
    finally:
        donor.stop()


def test_bootstrap_hands_off_to_gossip_at_the_watermark():
    """Writes landing on the donor AFTER the snapshot was captured
    arrive through normal anti-entropy, and the pull counter shows the
    tail ONLY — the watermark contract."""
    donor_store = RelayStore()
    _seed(donor_store, owners=12, per_minute=10, minutes=2)
    donor = RelayServer(donor_store, peers=[]).start()
    dest = RelayStore()
    mgr = ReplicationManager(
        dest, [donor.url], replica_id="wm-peer", http_post=_fast_post,
        bootstrap_lag_owners=4,
    )
    try:
        mgr.run_once()
        assert _state(dest) == _state(donor_store)
        # Post-snapshot tail: 17 fresh rows on one owner.
        donor_store.add_messages("owner003", _msgs("4" * 16, 30, 0, 17))
        mgr.run_once()
        assert _state(dest) == _state(donor_store)
        assert metrics.get_counter(
            "evolu_repl_messages_pulled_total", replica="wm-peer", peer=donor.url
        ) == 17
        # Routine fleet growth stays incremental: ONE new owner on the
        # donor must ride a ranged pull, never a full re-bootstrap —
        # even at bootstrap_lag_owners=4 with unknown(1) < majority.
        donor_store.add_messages("brand-new-owner", _msgs("9" * 16, 31, 0, 6))
        mgr.run_once()
        assert _state(dest) == _state(donor_store)
        assert metrics.get_counter(
            "evolu_snap_installs_total", result="ok",
            replica="wm-peer", peer=donor.url,
        ) == 1, "a single new owner re-triggered a full snapshot bootstrap"
        assert metrics.get_counter(
            "evolu_repl_messages_pulled_total", replica="wm-peer", peer=donor.url
        ) == 23
    finally:
        mgr.stop()
        donor.stop()
        dest.close()


def test_lagging_peer_bootstrap_merges_local_only_rows():
    """A lagging (NOT empty) peer keeps rows the donor never had: they
    merge into the installed snapshot through the changes==1 XOR gate,
    so the swapped-in trees are exact unions (recomputable from the
    swapped-in tables)."""
    from evolu_tpu.core.merkle import (
        apply_prefix_xors, merkle_tree_to_string, minute_deltas_host,
    )

    donor_store = RelayStore()
    _seed(donor_store, owners=20, per_minute=8, minutes=2)
    donor = RelayServer(donor_store, peers=[]).start()
    dest = RelayStore()
    # The lagging peer holds an OLD subset of one donor owner (same
    # node id → identical timestamps → true subset) plus a local-only
    # owner and local-only rows the donor lacks entirely.
    dest.add_messages("owner001", _msgs(f"{2:016x}", 0, 0, 8))
    local_only = _msgs("e" * 16, 40, 0, 5)
    dest.add_messages("owner001", local_only)
    dest.add_messages("local-owner", _msgs("f" * 16, 41, 0, 3))
    mgr = ReplicationManager(
        dest, [donor.url], replica_id="lag-peer", http_post=_fast_post,
        bootstrap_lag_owners=4,
    )
    try:
        mgr.run_once()
        got = _state(dest)
        donor_state = _state(donor_store)
        # Donor rows all present; local-only rows survived the swap.
        assert set(got) == set(donor_state) | {"local-owner"}
        assert len(got["owner001"][1]) == len(donor_state["owner001"][1]) + 5
        assert len(got["local-owner"][1]) == 3
        # Every swapped-in tree is exactly the recompute of its rows.
        for uid, (tree_text, rows) in got.items():
            deltas, _d = minute_deltas_host([m.timestamp for m in rows])
            assert tree_text == merkle_tree_to_string(
                apply_prefix_xors({}, deltas)), uid
    finally:
        mgr.stop()
        donor.stop()
        dest.close()


# -- integrity gates --


def _corrupting_post(flip_in_chunks=True):
    """Transport that flips one payload bit in every chunk response."""

    def post(url, body):
        out = _fast_post(url, body)
        if flip_in_chunks and url.endswith("/replicate/snapshot/chunk"):
            chunk = protocol.decode_snapshot_chunk(out)
            bad = bytearray(chunk.payload)
            bad[len(bad) // 2] ^= 0x40
            out = protocol.encode_snapshot_chunk(
                protocol.SnapshotChunk(
                    chunk.snapshot_id, chunk.index, chunk.crc, bytes(bad)
                )
            )
        return out

    return post


def test_corrupted_chunk_aborts_install_live_tables_untouched():
    donor_store = RelayStore()
    _seed(donor_store, owners=6, per_minute=10, minutes=2)
    donor = RelayServer(donor_store, peers=[]).start()
    dest = RelayStore()
    dest.add_messages("pre-existing", _msgs("a" * 16, 0, 0, 4))
    before = _state(dest)
    mgr = ReplicationManager(
        dest, [donor.url], replica_id="corrupt-peer",
        http_post=_corrupting_post(), bootstrap_lag_owners=1,
    )
    try:
        with pytest.raises(snapshot.SnapshotInstallError):
            mgr.bootstrap_from(donor.url)
        assert _state(dest) == before  # live tables untouched
        # Install state dropped: nothing to resume from.
        assert snapshot.SnapshotInstaller(dest).pending() is None
        assert metrics.get_counter(
            "evolu_snap_installs_total", result="error",
            replica="corrupt-peer", peer=donor.url,
        ) >= 1
    finally:
        mgr.stop()
        donor.stop()
        dest.close()


def test_verify_rejects_tampered_tree_byte_identity():
    """The golden-parity gate: a snapshot whose shipped tree text is
    NOT byte-identical to the recompute from its own rows aborts, even
    when manifest digests are made to agree with the tampered text."""
    store = RelayStore()
    _seed(store, owners=3, per_minute=6, minutes=2)
    manifest, chunks = snapshot.capture_snapshot(store)
    stream = b"".join(chunks)
    recs = list(snapshot.iter_records(stream))
    # Tamper one owner's TREE text (flip a hash digit), rebuild the
    # stream AND a consistent manifest (crc/root updated to the
    # tampered text — only byte-recompute parity can catch it).
    out = []
    tampered_uid = None
    for r in recs:
        if r[0] == "T" and tampered_uid is None:
            from evolu_tpu.core.merkle import (
                merkle_tree_from_string, merkle_tree_to_string,
            )
            from evolu_tpu.core.murmur import to_int32

            tampered_uid = r[1]
            t = merkle_tree_from_string(r[2])
            t["hash"] = to_int32((t.get("hash") or 0) ^ 1)
            bad_tree = merkle_tree_to_string(t)
            out.append(snapshot._frame_tree(r[1], bad_tree))
            owners = tuple(
                (u, merkle_tree_from_string(bad_tree).get("hash") or 0,
                 zlib.crc32(bad_tree.encode())) if u == r[1] else (u, rh, tc)
                for u, rh, tc in manifest.owners
            )
        elif r[0] == "T":
            out.append(snapshot._frame_tree(r[1], r[2]))
        else:
            out.append(snapshot._frame_message(r[1], r[2], r[3]))
    bad_stream = b"".join(out)
    bad_manifest = protocol.SnapshotManifest(
        manifest.snapshot_id, (len(bad_stream),), (zlib.crc32(bad_stream),),
        owners, manifest.message_count, len(bad_stream),
    )
    dest = RelayStore()
    with pytest.raises(snapshot.SnapshotInstallError):
        snapshot.install_stream(dest, bad_manifest, [bad_stream])
    assert dest.user_ids() == []
    store.close()
    dest.close()


# -- resume --


class _FlakyTransport:
    """Fails every chunk leg after the first `allow` with a
    connection-level error — an interrupted bootstrap."""

    def __init__(self, allow):
        self.allow = allow
        self.chunk_posts = 0
        self.failing = True

    def post(self, url, body):
        if url.endswith("/replicate/snapshot/chunk"):
            if self.failing and self.chunk_posts >= self.allow:
                raise urllib.error.URLError("flaky (fault injection)")
            self.chunk_posts += 1
        return _fast_post(url, body)


def test_interrupted_fetch_resumes_from_persisted_watermark():
    """A bootstrap cut off mid-fetch resumes at the NEXT round from
    the persisted chunk watermark: completed chunks are not
    re-requested (donor-side per-index serve log), and the final state
    is byte-identical."""
    donor_store = RelayStore()
    _seed(donor_store, owners=10, per_minute=40, minutes=5, payload=b"x" * 40)
    donor = RelayServer(donor_store, peers=[]).start()
    served: list = []
    cache = donor.replication.snapshot_cache
    orig_chunk = cache.chunk
    cache.chunk = lambda sid, i: (served.append(i), orig_chunk(sid, i))[1]
    dest = RelayStore()
    flaky = _FlakyTransport(allow=2)
    mgr = ReplicationManager(
        dest, [donor.url], replica_id="resume-peer", http_post=flaky.post,
        bootstrap_lag_owners=1, snapshot_chunk_bytes=64 * 1024,
    )
    try:
        with pytest.raises(urllib.error.URLError):
            mgr.bootstrap_from(donor.url)
        pending = snapshot.SnapshotInstaller(dest).pending()
        assert pending is not None and pending["next_chunk"] == 2
        assert len(pending["manifest"].chunk_sizes) > 3
        flaky.failing = False
        mgr.bootstrap_from(donor.url)  # resumes — no restart
        assert _state(dest) == _state(donor_store)
        # Chunks 0 and 1 were served exactly once each: the resume
        # started at the watermark, not at zero.
        assert served.count(0) == 1 and served.count(1) == 1, served
        assert metrics.get_counter(
            "evolu_snap_resumes_total", replica="resume-peer", peer=donor.url
        ) == 1
    finally:
        mgr.stop()
        donor.stop()
        dest.close()


def test_multi_peer_resume_sticks_to_the_original_donor():
    """In a multi-peer mesh, the first round after a crash may target a
    DIFFERENT peer than the one the persisted watermark came from; the
    resume must redirect to the original donor (only it still serves
    the snapshot id) instead of discarding completed chunks."""
    donor_store = RelayStore()
    _seed(donor_store, owners=10, per_minute=40, minutes=5, payload=b"m" * 40)
    donor = RelayServer(donor_store, peers=[]).start()
    decoy_store = RelayStore()
    _seed(decoy_store, owners=2, per_minute=4, minutes=1)
    decoy = RelayServer(decoy_store, peers=[]).start()
    decoy_chunks: list = []
    dc = decoy.replication.snapshot_cache
    orig_dc = dc.chunk
    dc.chunk = lambda sid, i: (decoy_chunks.append(i), orig_dc(sid, i))[1]
    donor_served: list = []
    cache = donor.replication.snapshot_cache
    orig_chunk = cache.chunk
    cache.chunk = lambda sid, i: (donor_served.append(i), orig_chunk(sid, i))[1]
    dest = RelayStore()
    flaky = _FlakyTransport(allow=2)
    mgr = ReplicationManager(
        dest, [decoy.url, donor.url], replica_id="multi-peer",
        http_post=flaky.post, bootstrap_lag_owners=1,
        snapshot_chunk_bytes=64 * 1024,
    )
    try:
        with pytest.raises(urllib.error.URLError):
            mgr.bootstrap_from(donor.url)  # interrupted after 2 chunks
        flaky.failing = False
        # "Restart": the next round happens to target the DECOY peer.
        mgr.bootstrap_from(decoy.url)
        assert _state(dest) == _state(donor_store)  # donor's data, not decoy's
        assert donor_served.count(0) == 1 and donor_served.count(1) == 1
        assert not decoy_chunks, "resume refetched from the wrong peer"
    finally:
        mgr.stop()
        donor.stop()
        decoy.stop()
        dest.close()


def test_stranded_mid_swap_install_finishes_on_the_next_round():
    """A crash BETWEEN shard swaps leaves a verified install half
    swapped in; the half-swapped live tables may advertise enough
    owners that the bootstrap trigger never fires again — any
    manager's first round must finish the pending swap regardless."""
    donor_store = RelayStore()
    _seed(donor_store, owners=10, per_minute=8, minutes=2)
    donor = RelayServer(donor_store, peers=[]).start()
    dest = ShardedRelayStore(shards=2)
    try:
        # Reproduce the crash state by driving the installer directly:
        # full fetch + verify, phase=swap persisted, only shard 0
        # actually swapped (the process "died" before shard 1).
        manifest, chunks = snapshot.capture_snapshot(donor_store)
        inst = snapshot.SnapshotInstaller(dest)
        inst.begin(manifest, donor.url)
        for i, payload in enumerate(chunks):
            inst.install_chunk(i, payload, expected_crc=manifest.chunk_crcs[i])
        inst.verify(manifest)
        inst._state_set(phase="swap")
        db = dest.shards[0].db
        with snapshot._exclusive_txn(db):
            db.run('DROP TABLE "message"')
            db.run('ALTER TABLE "messageBsnap" RENAME TO "message"')
            db.run('DROP TABLE "merkleTree"')
            db.run('ALTER TABLE "merkleTreeBsnap" RENAME TO "merkleTree"')
        assert _state(dest) != _state(donor_store)  # half swapped

        # "Restart": a fresh manager whose threshold will NOT re-arm
        # bootstrap (shard 0's owners are already visible) still
        # finishes the pending swap on its first round.
        mgr = ReplicationManager(
            dest, [donor.url], replica_id="strand-peer", http_post=_fast_post,
            bootstrap_lag_owners=50,
        )
        mgr.run_once()
        assert _state(dest) == _state(donor_store)
        assert snapshot.SnapshotInstaller(dest).pending() is None
        mgr.stop()
    finally:
        donor.stop()
        dest.close()


def test_expired_snapshot_restarts_fresh():
    """A donor that no longer serves the snapshot id (cache expiry /
    restart) answers 400 on the chunk leg: the puller drops its stale
    watermark and the next attempt bootstraps fresh to byte-identity."""
    donor_store = RelayStore()
    _seed(donor_store, owners=8, per_minute=30, minutes=3, payload=b"y" * 40)
    donor = RelayServer(donor_store, peers=[]).start()
    dest = RelayStore()
    flaky = _FlakyTransport(allow=1)
    mgr = ReplicationManager(
        dest, [donor.url], replica_id="expire-peer", http_post=flaky.post,
        bootstrap_lag_owners=1, snapshot_chunk_bytes=64 * 1024,
    )
    try:
        with pytest.raises(urllib.error.URLError):
            mgr.bootstrap_from(donor.url)
        donor.replication.snapshot_cache._entries.clear()  # donor "restarted"
        flaky.failing = False
        with pytest.raises(urllib.error.HTTPError):  # 400 → state dropped
            mgr.bootstrap_from(donor.url)
        assert snapshot.SnapshotInstaller(dest).pending() is None
        mgr.bootstrap_from(donor.url)  # fresh bootstrap succeeds
        assert _state(dest) == _state(donor_store)
    finally:
        mgr.stop()
        donor.stop()
        dest.close()


def _read_lines_until(proc, predicate, deadline_s):
    """Read child stdout lines until predicate(line) or deadline."""
    deadline = time.time() + deadline_s
    lines = []
    while time.time() < deadline:
        r, _w, _x = select.select([proc.stdout], [], [], 0.1)
        if not r:
            if proc.poll() is not None:
                break
            continue
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line.strip())
        if predicate(line):
            return lines
    return lines


def test_sigkill_between_chunks_resumes_from_watermark(tmp_path):
    """The satellite crash test: SIGKILL the bootstrapping relay
    PROCESS between snapshot chunks, restart it, and the install
    resumes from the persisted watermark — completed chunks are not
    re-transferred (donor-side per-index serve log) and the final
    trees/tables are byte-identical to the donor's."""
    donor_store = RelayStore()
    _seed(donor_store, owners=8, per_minute=50, minutes=4, payload=b"z" * 48)
    donor = RelayServer(donor_store, peers=[]).start()
    served: list = []
    cache = donor.replication.snapshot_cache
    orig_chunk = cache.chunk
    cache.chunk = lambda sid, i: (served.append(i), orig_chunk(sid, i))[1]

    donor_crc = 0
    for u in sorted(donor_store.user_ids()):
        donor_crc = zlib.crc32(donor_store.get_merkle_tree_string(u).encode(), donor_crc)
        for m in donor_store.replica_messages(u, ""):
            donor_crc = zlib.crc32(m.timestamp.encode(), donor_crc)
            donor_crc = zlib.crc32(m.content, donor_crc)

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    env["JAX_PLATFORMS"] = "cpu"
    for v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
        env.pop(v, None)
    db_path = str(tmp_path / "victim.db")
    worker = os.path.join(_REPO, "tests", "_snapshot_bootstrap_worker.py")

    try:
        # Run 1: slow installs; SIGKILL after the chunk-1 watermark
        # commits (the CHUNK line prints post-commit, then sleeps).
        p1 = subprocess.Popen(
            [sys.executable, worker, donor.url, db_path, "0.4"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        lines = _read_lines_until(p1, lambda ln: "CHUNK 1" in ln, 60)
        assert any("CHUNK 1" in ln for ln in lines), lines
        os.kill(p1.pid, signal.SIGKILL)
        p1.wait(timeout=10)
        completed_before_kill = sum(1 for ln in lines if ln.startswith("CHUNK"))
        serves_before_kill = list(served)
        assert completed_before_kill >= 2

        # Run 2: fresh process over the same DB file — must resume.
        p2 = subprocess.Popen(
            [sys.executable, worker, donor.url, db_path, "0"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        lines2 = _read_lines_until(p2, lambda ln: ln.startswith("DONE"), 120)
        p2.wait(timeout=10)
        done = [ln for ln in lines2 if ln.startswith("DONE")]
        assert done, lines2
        assert done[0] == f"DONE crc={donor_crc:08x}"  # byte-identical end state

        # Resume, not restart: the second run's chunk requests start at
        # the persisted watermark — every chunk completed before the
        # kill was transferred exactly once across both runs.
        run2_serves = served[len(serves_before_kill):]
        assert run2_serves, "second run never fetched (no resume?)"
        assert min(run2_serves) >= completed_before_kill, (
            serves_before_kill, run2_serves, completed_before_kill,
        )
        for i in range(completed_before_kill):
            assert served.count(i) == 1, (i, served)
    finally:
        donor.stop()


def test_client_write_accepted_mid_install_survives_the_swap(monkeypatch):
    """A write the relay ACKs while a bootstrap install is in flight
    must not vanish when the side tables swap in: the swap transaction
    re-merges live rows through the XOR gate before the rename
    (review finding — the merge used to run before the swap, leaving
    a drop window)."""
    import threading

    donor_store = RelayStore()
    _seed(donor_store, owners=8, per_minute=40, minutes=4, payload=b"w" * 40)
    donor = RelayServer(donor_store, peers=[]).start()
    dest = RelayStore()
    orig = snapshot.SnapshotInstaller.install_chunk

    def slow(self, i, p, expected_crc=None):
        n = orig(self, i, p, expected_crc)
        time.sleep(0.15)
        return n

    monkeypatch.setattr(snapshot.SnapshotInstaller, "install_chunk", slow)
    mgr = ReplicationManager(
        dest, [donor.url], replica_id="midwrite-peer", http_post=_fast_post,
        bootstrap_lag_owners=1, snapshot_chunk_bytes=64 * 1024,
    )
    try:
        t = threading.Thread(target=lambda: mgr.bootstrap_from(donor.url))
        t.start()
        time.sleep(0.2)  # mid-install: the relay ACKs a client write
        dest.add_messages("mid-install-owner", _msgs("d" * 16, 99, 0, 3))
        t.join(timeout=60)
        assert not t.is_alive()
        got = _state(dest)
        assert len(got.get("mid-install-owner", ("", ()))[1]) == 3, (
            "acknowledged mid-install write vanished in the swap"
        )
        donor_state = _state(donor_store)
        assert all(got[u] == donor_state[u] for u in donor_state)
    finally:
        mgr.stop()
        donor.stop()
        dest.close()


def test_capture_waits_out_foreign_open_transactions():
    """The batch engine's explicit begin/commit protocol releases the
    db lock between statements; a capture (or install/swap) landing
    mid-batch must WAIT for the commit, never join the foreign
    transaction — joining would snapshot uncommitted rows (or commit
    half a swap with someone else's batch)."""
    import threading

    from evolu_tpu.storage.native import native_available

    if not native_available():
        pytest.skip("explicit begin/commit lives on the native backend")
    store = RelayStore(backend="native")
    _seed(store, owners=2, per_minute=5, minutes=1)
    db = store.db
    db.begin()  # the engine's shard-parallel ingest shape
    db.run(
        'INSERT INTO "message" ("timestamp", "userId", "content") '
        "VALUES (?, ?, ?)",
        ("t" * 46, "owner000", b"mid-batch"),
    )
    result = {}
    t = threading.Thread(
        target=lambda: result.update(m=snapshot.capture_snapshot(store)[0])
    )
    t.start()
    time.sleep(0.25)
    assert t.is_alive(), "capture joined a foreign open transaction"
    db.commit()
    t.join(timeout=10)
    assert not t.is_alive()
    # The capture ran AFTER the commit: it sees the committed batch,
    # all 11 rows — never a torn mid-transaction view.
    assert result["m"].message_count == 11
    store.close()


# -- local checkpoints --


def test_checkpoint_write_restore_byte_identical(tmp_path):
    src = ShardedRelayStore(shards=2)
    _seed(src, owners=9, per_minute=11, minutes=3)
    path = str(tmp_path / "relay.checkpoint")
    snapshot.write_checkpoint(src, path)
    assert not os.path.exists(path + ".tmp")  # atomic: tmp renamed away

    # Restore into a DIFFERENT sharding layout: rows re-route by owner.
    dest = ShardedRelayStore(shards=4)
    snapshot.restore_checkpoint(dest, path)
    assert _state(dest) == _state(src)

    # Corruption is detected before anything installs.
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) - 20)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes((b[0] ^ 0x10,)))
    fresh = RelayStore()
    with pytest.raises(ValueError):
        snapshot.restore_checkpoint(fresh, path)
    assert fresh.user_ids() == []
    src.close()
    dest.close()
    fresh.close()


def test_periodic_checkpointer_via_relay_server(tmp_path):
    path = str(tmp_path / "live.checkpoint")
    store = RelayStore()
    _seed(store, owners=3, per_minute=5, minutes=1)
    server = RelayServer(store, checkpoint_interval_s=0.05,
                         checkpoint_path=path).start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not os.path.exists(path):
            time.sleep(0.02)
        assert os.path.exists(path), "periodic checkpoint never written"
    finally:
        server.stop()
    restored = RelayStore()
    snapshot.restore_checkpoint(restored, path)
    assert sorted(restored.user_ids()) == ["owner000", "owner001", "owner002"]
    restored.close()


def test_relay_server_requires_checkpoint_path_for_memory_stores():
    with pytest.raises(ValueError):
        RelayServer(RelayStore(), checkpoint_interval_s=1.0)


def test_config_defaults_flow_into_the_replication_manager():
    """utils/config.py fleet knobs are LIVE process defaults: any
    constructor arg left at None resolves from default_config."""
    from evolu_tpu.utils.config import Config, default_config, set_config

    old = default_config
    store = RelayStore()
    try:
        set_config(Config(pull_messages_per_owner=77,
                          pull_messages_per_response=555,
                          bootstrap_lag_owners=5))
        mgr = ReplicationManager(store, [], replica_id="cfg-peer")
        assert mgr.pull_messages_per_owner == 77
        assert mgr.pull_messages_per_response == 555
        assert mgr.bootstrap_lag_owners == 5
        # Explicit constructor args still win over the config.
        mgr2 = ReplicationManager(store, [], replica_id="cfg-peer2",
                                  pull_messages_per_owner=11)
        assert mgr2.pull_messages_per_owner == 11
        mgr.stop()
        mgr2.stop()
    finally:
        set_config(old)
        store.close()


# -- observability surface --


def test_snapshot_stats_and_metrics_surface():
    import json
    import urllib.request

    donor_store = RelayStore()
    _seed(donor_store, owners=5, per_minute=6, minutes=1)
    donor = RelayServer(donor_store, peers=[]).start()
    dest_store = RelayStore()
    dest = RelayServer(
        dest_store, peers=[donor.url], replication_interval_s=3600,
        bootstrap_lag_owners=1,
    ).start()
    try:
        deadline = time.time() + 20
        while time.time() < deadline and _state(dest_store) != _state(donor_store):
            time.sleep(0.05)
        assert _state(dest_store) == _state(donor_store)
        with urllib.request.urlopen(dest.url + "/stats", timeout=10) as r:
            stats = json.loads(r.read())
        (peer,) = stats["replication"]["peers"]
        assert peer["snapshot_bootstraps"] >= 1
        assert peer["snapshot_chunks_fetched"] >= 1
        assert peer["snapshot_bytes_fetched"] > 0
        with urllib.request.urlopen(donor.url + "/stats", timeout=10) as r:
            donor_stats = json.loads(r.read())
        snap = donor_stats["replication"]["snapshot"]
        assert snap["captures"] >= 1
        assert snap["chunks_served"] >= 1
        assert snap["capture_rows"] >= 30
        with urllib.request.urlopen(donor.url + "/metrics", timeout=10) as r:
            prom = r.read().decode()
        assert "evolu_snap_captures_total" in prom
        assert "evolu_snap_chunks_served_total" in prom
    finally:
        dest.stop()
        donor.stop()
