"""Stage-anatomy plane (evolu_tpu/obs/anatomy.py + the ablation
harness benchmarks/stage_anatomy.py) — registry shape and digest
stability, roofline floor pricing against the recorded v5e laws,
unknown-platform unpriced behavior, the evolu_stage_* metrics family
(histograms/counters/gauges, over-floor flagging past warmup, the
decayed slope/fixed fit recovering a synthetic cost law, runtime share
gauges), kernel-span folding through utils.log.span, the /stats
payload, and registry↔harness agreement (variant arity, device-stage
order, truncated-variant structural containment)."""

import json
import os
import sys

import pytest

from evolu_tpu.obs import anatomy, metrics
from evolu_tpu.utils.log import logger, span

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))


@pytest.fixture(autouse=True)
def _clean_slate():
    logger.clear()  # resets metrics registry + anatomy accumulators
    prev = anatomy.get_platform()
    yield
    anatomy.set_platform(prev)
    logger.configure(False)
    logger.clear()


# --- registry shape + digests ---


def test_registry_shape():
    names = [s.name for s in anatomy.STAGES]
    assert names == [
        "key_sort", "plan_compare", "hash_render", "minute_fold",
        "delta_encode", "pull_wave", "device_dispatch", "host_apply",
    ]
    device = [s for s in anatomy.STAGES if s.kind == "device"]
    assert len(device) == 5
    # Device stages chain: each stage's inputs come from prior outputs
    # or the kernel's own input columns.
    produced = {"cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix"}
    for s in device:
        assert set(s.inputs) <= produced, (s.name, s.inputs)
        produced |= set(s.outputs)
    # Every price term names a real law key in EVERY platform's laws
    # (cpu and tpu rows must stay key-compatible).
    for s in anatomy.STAGES:
        for law_key, unit in s.price:
            if unit == "device_pipeline":
                continue
            for plat, laws in anatomy.COST_LAWS.items():
                assert law_key in laws, (s.name, law_key, plat)


def test_registry_digest_is_stable_and_law_sensitive():
    d1 = anatomy.registry_digest()
    assert d1 == anatomy.registry_digest()
    assert len(d1) == 8 and int(d1, 16) >= 0
    old = anatomy.COST_LAWS["tpu"]["sort_key_ms_per_1m"]
    try:
        anatomy.COST_LAWS["tpu"]["sort_key_ms_per_1m"] = old * 2
        assert anatomy.registry_digest() != d1  # re-pricing moves the gate
    finally:
        anatomy.COST_LAWS["tpu"]["sort_key_ms_per_1m"] = old


# --- floor pricing ---


def test_floor_prices_v5e_laws_exactly():
    # key_sort at 1M rows = 1.5 (key) + 2 × 0.75 (payloads) = 3.0 ms.
    assert anatomy.floor_ms("key_sort", rows=1_000_000,
                            platform="tpu") == pytest.approx(3.0)
    # pull_wave is bandwidth-priced: 17 MB at 17 MB/s = 1000 ms.
    assert anatomy.floor_ms("pull_wave", nbytes=17_000_000,
                            platform="tpu") == pytest.approx(1000.0)
    # host_apply is throughput-priced: 720k rows at 720k rows/s = 1 s.
    assert anatomy.floor_ms("host_apply", rows=720_000,
                            platform="tpu") == pytest.approx(1000.0)
    # device_dispatch = fixed RTT + the whole device pipeline at size.
    dev_sum = sum(
        anatomy.floor_ms(s.name, rows=1_000_000, platform="tpu")
        for s in anatomy.STAGES if s.kind == "device"
    )
    assert anatomy.floor_ms("device_dispatch", rows=1_000_000,
                            platform="tpu") == pytest.approx(101.0 + dev_sum)
    # Span targets price as the sum of their mapped stages.
    merkle = sum(
        anatomy.floor_ms(s, rows=1_000_000, platform="tpu")
        for s in ("hash_render", "minute_fold", "delta_encode")
    )
    assert anatomy.floor_ms("kernel:merkle", rows=1_000_000,
                            platform="tpu") == pytest.approx(merkle)


def test_unknown_platform_and_stage_are_unpriced():
    assert anatomy.floor_ms("key_sort", rows=1 << 20, platform="riscv") == 0.0
    assert anatomy.floor_ms("no_such_stage", rows=1 << 20, platform="tpu") == 0.0
    anatomy.set_platform("riscv")
    assert anatomy.floor_ms("key_sort", rows=1 << 20) == 0.0


# --- the evolu_stage_* family ---


def test_record_stage_emits_family():
    anatomy.set_platform("tpu")
    anatomy.record_stage("host_apply", 0.010, rows=7200)  # floor = 10 ms
    assert metrics.get_counter("evolu_stage_seconds_total",
                               stage="host_apply") == pytest.approx(0.010)
    assert metrics.get_counter("evolu_stage_rows_total",
                               stage="host_apply") == 7200
    _, _, _, count = metrics.registry.get_histogram("evolu_stage_ms",
                                                    stage="host_apply")
    assert count == 1
    assert metrics.registry.get_gauge(
        "evolu_stage_floor_ms", stage="host_apply") == pytest.approx(10.0)
    assert metrics.registry.get_gauge(
        "evolu_stage_over_floor_ratio", stage="host_apply"
    ) == pytest.approx(1.0)


def test_over_floor_flags_only_past_warmup():
    anatomy.set_platform("tpu")
    # floor = 10 ms; 100 ms is 10× over FLOOR_FACTOR=4.
    for _ in range(2):  # warmup records never flag (compile time)
        anatomy.record_stage("host_apply", 0.100, rows=7200)
    assert metrics.get_counter("evolu_stage_over_floor_total",
                               stage="host_apply") == 0
    anatomy.record_stage("host_apply", 0.100, rows=7200)
    assert metrics.get_counter("evolu_stage_over_floor_total",
                               stage="host_apply") == 1
    anatomy.record_stage("host_apply", 0.011, rows=7200)  # healthy: no flag
    assert metrics.get_counter("evolu_stage_over_floor_total",
                               stage="host_apply") == 1


def test_slope_fit_recovers_synthetic_law():
    # Synthetic stage law: 5 ms fixed + 2 µs/row. The decayed online
    # fit must separate intercept from slope (the wall/count trap).
    anatomy.set_platform("unknown-bench")
    for rows in (1000, 4000, 16000, 2000, 8000, 32000):
        anatomy.record_stage("device_dispatch", (5.0 + 0.002 * rows) / 1e3,
                             rows=rows)
    slope = metrics.registry.get_gauge("evolu_stage_slope_ns_per_row",
                                       stage="device_dispatch")
    fixed = metrics.registry.get_gauge("evolu_stage_fixed_ms",
                                       stage="device_dispatch")
    assert slope == pytest.approx(2000.0, rel=0.05)  # 2 µs = 2000 ns/row
    assert fixed == pytest.approx(5.0, rel=0.05)


def test_runtime_share_gauges():
    anatomy.set_platform("unknown-bench")
    anatomy.record_stage("device_dispatch", 0.030, rows=100)
    anatomy.record_stage("pull_wave", 0.010, nbytes=1000)
    anatomy.record_stage("host_apply", 0.060, rows=100)
    total = 0.030 + 0.010 + 0.060
    assert metrics.registry.get_gauge(
        "evolu_stage_share", stage="host_apply"
    ) == pytest.approx(0.060 / total)
    assert metrics.registry.get_gauge(
        "evolu_stage_share", stage="pull_wave"
    ) == pytest.approx(0.010 / total)
    payload = anatomy.stages_payload()
    assert payload["stages"]["device_dispatch"]["share"] == pytest.approx(
        0.030 / total)


def test_disabled_registry_records_nothing():
    metrics.set_enabled(False)
    try:
        anatomy.record_stage("host_apply", 0.5, rows=10_000)
    finally:
        metrics.set_enabled(True)
    assert anatomy.stages_payload()["stages"] == {}


def test_kernel_span_folds_into_family():
    anatomy.set_platform("tpu")
    with span("kernel:merkle", "t", n=1000):
        pass
    with span("host:apply", "t"):  # non-kernel spans stay out
        pass
    payload = anatomy.stages_payload()
    assert payload["stages"]["kernel:merkle"]["count"] == 1
    assert "host:apply" not in payload["stages"]
    assert metrics.get_counter("evolu_stage_rows_total",
                               stage="kernel:merkle") == 1000
    # The span target priced via its mapped stages.
    assert payload["stages"]["kernel:merkle"]["floor_ms"] == pytest.approx(
        anatomy.floor_ms("kernel:merkle", rows=1000, platform="tpu"))


def test_stages_payload_shape_and_reset():
    anatomy.set_platform("tpu")
    anatomy.record_stage("host_apply", 0.010, rows=7200)
    p = anatomy.stages_payload()
    assert p["platform"] == "tpu"
    assert p["registry_digest"] == anatomy.registry_digest()
    assert p["floor_factor"] == anatomy.FLOOR_FACTOR
    st = p["stages"]["host_apply"]
    assert st["count"] == 1
    assert st["ewma_ms"] == pytest.approx(10.0)
    json.dumps(p)  # must be JSON-clean for GET /stats
    logger.clear()
    assert anatomy.stages_payload()["stages"] == {}
    assert anatomy.get_platform() == "tpu"  # platform survives clear


# --- registry ↔ ablation-harness agreement ---


def test_harness_matches_registry():
    import stage_anatomy as sa

    assert sa.DEVICE_STAGES == tuple(
        s.name for s in anatomy.STAGES if s.kind == "device")
    # Cumulative arity: key_sort 3, +3, +2, +5, +3 = 16.
    assert [sa.variant_arity(s) for s in sa.DEVICE_STAGES] == [3, 6, 8, 13, 16]
    assert list(sa.stage_output_indices("hash_render")) == [6, 7]
    assert list(sa.stage_output_indices("key_sort")) == [0, 1, 2]


def test_truncated_variants_nest_structurally():
    """Each truncated variant's jaxpr primitive multiset must be a
    sub-multiset of the next one's — ablation only ever REMOVES tail
    work, so a stage can never change the upstream computation it
    claims to be measuring."""
    jax = pytest.importorskip("jax")
    import numpy as np

    import stage_anatomy as sa

    n = 256
    probe = (
        np.full(n, 0x7FFFFFFF, np.int32),
        np.zeros(n, np.uint64), np.zeros(n, np.uint64),
        np.zeros(n, np.uint64), np.zeros(n, np.uint64),
        np.zeros(n, np.int64),
    )
    from collections import Counter

    from evolu_tpu.parallel.mesh import create_mesh

    mesh = create_mesh()
    multisets = []
    with jax.enable_x64(True):
        for name in sa.DEVICE_STAGES:
            loop = sa.make_variant_loop(mesh, 1, sa.build_variant(name))
            jaxpr = jax.make_jaxpr(loop)(*probe)
            prims = []
            sa._collect_prims(jaxpr.jaxpr, prims)
            multisets.append(Counter(prims))
    for prev, cur in zip(multisets, multisets[1:]):
        assert not prev - cur, f"ablation removed upstream work: {prev - cur}"
    # And each stage genuinely adds primitives.
    for prev, cur in zip(multisets, multisets[1:]):
        assert cur - prev
