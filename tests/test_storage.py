"""Storage bootstrap, schema evolution, clock persistence, identity."""

import pytest

from evolu_tpu.core.mnemonic import generate_mnemonic, validate_mnemonic
from evolu_tpu.core.ids import mnemonic_to_owner_id
from evolu_tpu.core.types import CrdtClock, TableDefinition, Timestamp
from evolu_tpu.storage import (
    delete_all_tables,
    get_existing_tables,
    init_db_model,
    open_database,
    read_clock,
    update_clock,
    update_db_schema,
)
from evolu_tpu.core.merkle import insert_into_merkle_tree


def test_init_db_model_bootstrap_and_idempotence():
    db = open_database()
    owner = init_db_model(db, mnemonic="legal winner thank year wave sausage worth useful legal winner thank yellow")
    assert owner.id == mnemonic_to_owner_id(owner.mnemonic)
    assert len(owner.id) == 21
    # Idempotent: second init returns the same owner, keeps data.
    owner2 = init_db_model(db)
    assert owner2 == owner
    clock = read_clock(db)
    assert clock.timestamp.millis == 0 and clock.timestamp.counter == 0
    assert clock.merkle_tree == {}


def test_clock_roundtrip():
    db = open_database()
    init_db_model(db)
    t = Timestamp(1656873738591, 7, "aaaaaaaaaaaaaaaa")
    tree = insert_into_merkle_tree(t, {})
    update_clock(db, CrdtClock(t, tree))
    clock = read_clock(db)
    assert clock.timestamp == t
    assert clock.merkle_tree == tree


def test_update_db_schema_create_and_alter():
    db = open_database()
    init_db_model(db)
    update_db_schema(db, [TableDefinition.of("todo", ["title", "isCompleted"])])
    assert get_existing_tables(db) == {"todo"}
    cols = {r["name"] for r in db.exec_sql_query("PRAGMA table_info (todo)")}
    assert cols == {"id", "title", "isCompleted"}
    # Add-only migration: new column appears, nothing dropped.
    update_db_schema(db, [TableDefinition.of("todo", ["title", "isCompleted", "dueAt"])])
    cols = {r["name"] for r in db.exec_sql_query("PRAGMA table_info (todo)")}
    assert "dueAt" in cols and "title" in cols


def test_delete_all_tables():
    db = open_database()
    init_db_model(db)
    update_db_schema(db, [TableDefinition.of("todo", ["title"])])
    delete_all_tables(db)
    rows = db.exec_sql_query("SELECT name FROM sqlite_schema WHERE type='table'")
    assert rows == []


def test_transaction_rollback():
    db = open_database()
    init_db_model(db)
    update_db_schema(db, [TableDefinition.of("todo", ["title"])])
    with pytest.raises(RuntimeError):
        with db.transaction():
            db.run('INSERT INTO "todo" ("id", "title") VALUES (?, ?)', ("x" * 21, "a"))
            raise RuntimeError("boom")
    assert db.exec_sql_query('SELECT * FROM "todo"') == []


def test_mnemonic_generate_validate():
    m = generate_mnemonic()
    assert len(m.split(" ")) == 12
    assert validate_mnemonic(m)
    assert not validate_mnemonic("abandon " * 12)
    # BIP-39 spec test vector (entropy 0x7f...7f).
    assert validate_mnemonic(
        "legal winner thank year wave sausage worth useful legal winner thank yellow"
    )
    assert not validate_mnemonic("not a mnemonic at all")
