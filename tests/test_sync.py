"""Sync-layer tests: protobuf wire round-trips, OpenPGP crypto, relay
store semantics, and full client↔relay↔client convergence over HTTP.

The reference never tests this layer (SURVEY.md §4); the convergence
test here is the N-replica integration test the build plan requires.
"""

import pathlib
import shutil
import subprocess
import threading

import pytest

from evolu_tpu.api import model
from evolu_tpu.api.query import table
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.runtime.client import create_evolu
from evolu_tpu.sync import protocol
from evolu_tpu.sync.client import SyncTransport, connect, decrypt_messages, encrypt_messages
from evolu_tpu.sync.crypto import PgpError, decrypt_symmetric, encrypt_symmetric
from evolu_tpu.server.relay import RelayServer, RelayStore
from evolu_tpu.utils.config import Config

TODO_SCHEMA = {"todo": ("title", "isCompleted", *model.COMMON_COLUMNS)}
TS = "2024-01-15T10:30:00.123Z-0001-89e3b4f11a2c5d70"
FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures"


# --- protocol ---


@pytest.mark.parametrize(
    "value",
    ["hello", "", "ünïcode ✓", 0, 1, -1, 2**31 - 1, -(2**31), None, 3.25, -1e300],
)
def test_content_roundtrip(value):
    data = protocol.encode_content("todo", "row1", "title", value)
    assert protocol.decode_content(data) == ("todo", "row1", "title", value)


def test_sync_request_roundtrip():
    msgs = (
        protocol.EncryptedCrdtMessage(TS, b"\x01\x02\x03"),
        protocol.EncryptedCrdtMessage(TS.replace("00.123", "59.999"), b""),
    )
    req = protocol.SyncRequest(msgs, "owner123", "89e3b4f11a2c5d70", '{"hash":1}')
    assert protocol.decode_sync_request(protocol.encode_sync_request(req)) == req


def test_sync_response_roundtrip():
    resp = protocol.SyncResponse(
        (protocol.EncryptedCrdtMessage(TS, b"\xff" * 300),), '{"hash":-5}'
    )
    assert protocol.decode_sync_response(protocol.encode_sync_response(resp)) == resp


def test_protocol_interop_with_google_protobuf():
    """Cross-check our hand-rolled encoder against the protoc runtime
    parsing the reference's .proto schema shape."""
    from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

    pool = descriptor_pool.DescriptorPool()
    f = descriptor_pb2.FileDescriptorProto()
    f.name = "t.proto"
    f.syntax = "proto3"
    m = f.message_type.add()
    m.name = "CrdtMessageContent"
    for i, (name, type_) in enumerate(
        [("table", 9), ("row", 9), ("column", 9), ("stringValue", 9), ("numberValue", 5)],
        start=1,
    ):
        fld = m.field.add()
        fld.name, fld.number, fld.type, fld.label = name, i, type_, 1
    pool.Add(f)
    cls = message_factory.GetMessageClass(pool.FindMessageTypeByName("CrdtMessageContent"))
    parsed = cls.FromString(protocol.encode_content("todo", "r", "c", -42))
    assert (parsed.table, parsed.row, parsed.column, parsed.numberValue) == ("todo", "r", "c", -42)
    # And decode protoc-encoded bytes with our decoder.
    theirs = cls(table="x", row="y", column="z", stringValue="v").SerializeToString()
    assert protocol.decode_content(theirs) == ("x", "y", "z", "v")


def test_sync_request_golden_fixture():
    """Frozen protoc-runtime-encoded SyncRequest bytes (the canonical
    proto3 encoding a protobuf-ts reference client emits for the same
    message — see tests/fixtures/make_protobuf_fixtures.py). Pins the
    decoder against reference-producible bytes, not a self-roundtrip,
    and the encoder to the byte-identical canonical form."""
    data = (FIXTURES / "protoc_sync_request.bin").read_bytes()
    req = protocol.decode_sync_request(data)
    assert req.user_id == "9f3c2b1a0d4e5f60718293a"
    assert req.node_id == "a1b2c3d4e5f60718"
    assert req.merkle_tree == '{"hash":12345,"2":{"hash":12345}}'
    assert [m.timestamp for m in req.messages] == [
        "2024-01-31T10:20:30.444Z-0000-a1b2c3d4e5f60718",
        "2024-01-31T10:20:30.444Z-0001-a1b2c3d4e5f60718",
    ]
    assert protocol.decode_content(req.messages[0].content) == (
        "todo", "B4UsGiFxpnc7SQaBSNy1u", "title", "hello",
    )
    assert req.messages[1].content == b"\x01\x02\x03"
    assert protocol.encode_sync_request(req) == data


# --- crypto ---


def test_encrypt_decrypt_roundtrip():
    pt = protocol.encode_content("todo", "row", "title", "secret value")
    ct = encrypt_symmetric(pt, "drastic monkey fiber")
    assert ct != pt and pt not in ct
    assert decrypt_symmetric(ct, "drastic monkey fiber") == pt


def test_wrong_password_fails():
    ct = encrypt_symmetric(b"data", "right password")
    with pytest.raises(PgpError):
        decrypt_symmetric(ct, "wrong password")


# --- cross-implementation OpenPGP interop (GnuPG) ---
#
# The reference encrypts with OpenPGP.js v5 (sync.worker.ts:59-91,
# s2kIterationCountByte: 0). OpenPGP.js cannot run here (no Node
# runtime), so interop is proven against GnuPG — an independent
# RFC 4880 implementation — in BOTH directions: frozen gpg-produced
# ciphertexts with the reference's exact parameters (AES-256,
# iterated+salted SHA-256 S2K, count 1024) must decrypt, and gpg must
# decrypt our encryptor's output live.

# Read from the committed fixture so the test stays in lockstep with
# regeneration (make_gpg_fixtures.py writes password + plaintext +
# ciphertexts together).
GPG_PASSWORD = (FIXTURES / "gpg_password.txt").read_text().strip()


@pytest.mark.parametrize(
    "name",
    [
        "gpg_aes256_s2k1024_none.pgp",
        "gpg_aes256_s2k1024_zip.pgp",
        "gpg_aes256_s2k1024_zlib.pgp",
    ],
)
def test_gpg_golden_ciphertext_decrypts(name):
    plaintext = (FIXTURES / "gpg_plaintext.bin").read_bytes()
    assert decrypt_symmetric((FIXTURES / name).read_bytes(), GPG_PASSWORD) == plaintext
    # The fixture plaintext is a real protobuf CrdtMessageContent.
    assert protocol.decode_content(plaintext) == (
        "todo", "B4UsGiFxpnc7SQaBSNy1u", "title", "Buy milk ✓ café",
    )


def test_decoder_fuzz_typed_errors():
    """Malformed wire bytes must raise the typed errors the relay and
    sync client key off (ValueError / PgpError) — never AttributeError,
    TypeError or IndexError (all three escaped before the _wire_decoder
    guard; found by fuzzing)."""
    import random

    rng = random.Random(5)
    decoders = (
        protocol.decode_sync_request,
        protocol.decode_sync_response,
        protocol.decode_encrypted_message,
        protocol.decode_content,
        protocol.scan_sync_response_capabilities,
    )
    for _ in range(1500):
        blob = rng.randbytes(rng.randrange(0, 120))
        for fn in decoders:
            try:
                fn(blob)
            except ValueError:
                pass  # the contract

    # Truncated fixed-width fields must REJECT, not decode garbage.
    with pytest.raises(ValueError):
        protocol.decode_content(b"\x31" + b"\x00\x01\x02")  # doubleValue, 3/8 bytes
    with pytest.raises(ValueError):
        protocol.decode_content(b"\x2d" + b"\x00")  # numberValue fixed32, 1/4 bytes

    ct = encrypt_symmetric(b"payload-bytes", "pw")
    for cut in range(len(ct)):
        with pytest.raises(PgpError):
            decrypt_symmetric(ct[:cut], "pw")
    # Legacy-SED with a short body must be PgpError (cryptography's
    # invalid-IV ValueError is wrapped), even with a vacuous key check.
    from evolu_tpu.sync.crypto import _new_packet
    skesk = ct[:15]  # tag-3 packet: 1 header + 1 len + 13 body bytes
    assert skesk[0] == 0xC3
    with pytest.raises(PgpError):
        decrypt_symmetric(skesk + _new_packet(9, b"\x00" * 10), "pw")
    for _ in range(800):
        corrupted = bytearray(ct)
        corrupted[rng.randrange(len(corrupted))] ^= 1 << rng.randrange(8)
        if bytes(corrupted) == ct:
            continue
        try:
            decrypt_symmetric(bytes(corrupted), "pw")
        except PgpError:
            pass  # the contract (a flip in the literal body may decrypt)


def test_gpg_rejects_nothing_we_accept_wrong_password():
    with pytest.raises(PgpError):
        decrypt_symmetric(
            (FIXTURES / "gpg_aes256_s2k1024_none.pgp").read_bytes(), "wrong"
        )


@pytest.mark.skipif(shutil.which("gpg") is None, reason="gpg not on PATH")
def test_gpg_decrypts_our_ciphertext(tmp_path):
    """The risk VERDICT.md flags: a packet-detail bug would make a real
    client unable to decrypt us and a self-roundtrip would never catch
    it. An independent implementation consuming our bytes does."""
    plaintext = protocol.encode_content("todo", "row-1", "title", "χρόνος ✓")
    ciphertext = encrypt_symmetric(plaintext, GPG_PASSWORD)
    result = subprocess.run(
        [
            "gpg", "--homedir", str(tmp_path), "--batch",
            "--pinentry-mode", "loopback", "--passphrase", GPG_PASSWORD,
            "--decrypt",
        ],
        input=ciphertext,
        capture_output=True,
    )
    assert result.returncode == 0, result.stderr.decode()
    assert result.stdout == plaintext


def test_ciphertext_is_nondeterministic():
    assert encrypt_symmetric(b"x", "p") != encrypt_symmetric(b"x", "p")


def test_mdc_tamper_detected():
    ct = bytearray(encrypt_symmetric(b"payload", "p"))
    ct[-5] ^= 0xFF
    with pytest.raises(PgpError):
        decrypt_symmetric(bytes(ct), "p")


def test_large_payload_roundtrip():
    pt = b"\x00\x01" * 10000
    assert decrypt_symmetric(encrypt_symmetric(pt, "p"), "p") == pt


def test_encrypt_decrypt_messages_pipeline():
    msgs = (
        CrdtMessage(TS, "todo", "r1", "title", "hello"),
        CrdtMessage(TS, "todo", "r1", "isCompleted", 1),
        CrdtMessage(TS, "todo", "r1", "note", None),
    )
    enc = encrypt_messages(msgs, "mnemonic words here")
    assert all(e.timestamp == TS for e in enc)  # timestamps stay plaintext
    assert decrypt_messages(enc, "mnemonic words here") == msgs


# --- relay store ---


def _enc(ts, payload=b"c"):
    return protocol.EncryptedCrdtMessage(ts, payload)


def test_relay_add_messages_idempotent():
    store = RelayStore()
    t1 = store.add_messages("u1", [_enc(TS)])
    t2 = store.add_messages("u1", [_enc(TS)])  # duplicate: changes==0, no XOR
    assert t1 == t2


def test_relay_sync_returns_missing_excluding_own_node():
    store = RelayStore()
    other = TS.replace("89e3b4f11a2c5d70", "aaaaaaaaaaaaaaaa")
    store.add_messages("u1", [_enc(TS), _enc(other, b"other")])
    # Client with empty tree and the first message's node id asks for a diff.
    from evolu_tpu.core.merkle import create_initial_merkle_tree, merkle_tree_to_string

    req = protocol.SyncRequest((), "u1", "89e3b4f11a2c5d70",
                               merkle_tree_to_string(create_initial_merkle_tree()))
    resp = store.sync(req)
    assert [m.timestamp for m in resp.messages] == [other]  # own node excluded


def test_relay_users_are_isolated():
    store = RelayStore()
    store.add_messages("u1", [_enc(TS)])
    from evolu_tpu.core.merkle import create_initial_merkle_tree, merkle_tree_to_string

    req = protocol.SyncRequest((), "u2", "bbbbbbbbbbbbbbbb",
                               merkle_tree_to_string(create_initial_merkle_tree()))
    resp = store.sync(req)
    assert resp.messages == () and resp.merkle_tree == "{}"


# --- end-to-end over HTTP ---


def _converged(*clients, query):
    rows = [c.query_once(query) for c in clients]
    return all(r == rows[0] for r in rows)


def test_clients_converge_through_relay():
    server = RelayServer().start()
    try:
        mnemonic = None
        config = Config(sync_url=server.url)
        a = create_evolu(TODO_SCHEMA, config=config)
        b = create_evolu(TODO_SCHEMA, config=config, mnemonic=a.owner.mnemonic)
        ta, tb = connect(a), connect(b)
        try:
            q = table("todo").select("id", "title").order_by("id").serialize()
            rid = a.create("todo", {"title": "from-a"})
            b.create("todo", {"title": "from-b"})
            # Let the push rounds land, then pull until converged.
            for _ in range(6):
                a.worker.flush(); ta.flush(); a.worker.flush()
                b.worker.flush(); tb.flush(); b.worker.flush()
                a.sync(refresh_queries=False); b.sync(refresh_queries=False)
            ra, rb = a.query_once(q), b.query_once(q)
            assert len(ra) == 2 and ra == rb, (ra, rb)
            assert a.get_error() is None and b.get_error() is None
            # A third device restores from the mnemonic alone (SURVEY §3.5).
            c = create_evolu(TODO_SCHEMA, config=config, mnemonic=a.owner.mnemonic)
            tc = connect(c)
            c.sync(refresh_queries=False)
            for _ in range(6):
                c.worker.flush(); tc.flush(); c.worker.flush()
                c.sync(refresh_queries=False)
            assert c.query_once(q) == ra
            c.dispose()
        finally:
            a.dispose(); b.dispose()
    finally:
        server.stop()


def test_offline_tolerance():
    """Unreachable relay: no error surfaces; mutations stay local."""
    config = Config(sync_url="http://127.0.0.1:9")  # discard port, refuses
    a = create_evolu(TODO_SCHEMA, config=config)
    transport = connect(a)
    try:
        a.create("todo", {"title": "offline"})
        a.worker.flush()
        transport.flush()
        q = table("todo").select("title").serialize()
        assert [r["title"] for r in a.query_once(q)] == ["offline"]
        assert a.get_error() is None
    finally:
        a.dispose()


def test_int64_and_doc_values_roundtrip_exact():
    for v in (2**53 + 1, -(2**63), 2**63 - 1, 2**31):
        data = protocol.encode_content("t", "r", "c", v)
        out = protocol.decode_content(data)[3]
        assert out == v and isinstance(out, int)
    with pytest.raises(TypeError):
        protocol.encode_content("t", "r", "c", 2**64)


def test_http_error_surfaces_but_offline_does_not():
    """4xx/5xx from the relay is a real error; refused connection is not."""
    import urllib.error
    from evolu_tpu.core.types import Owner
    from evolu_tpu.runtime.messages import SyncRequestInput

    errors = []

    def post_413(url, body):
        raise urllib.error.HTTPError(url, 413, "too large", {}, None)

    t = SyncTransport(Config(), on_receive=lambda *a: None,
                      on_error=errors.append, http_post=post_413)
    req = SyncRequestInput((), TS, "{}", Owner("o", "m"))
    t.request_sync(req)
    t.flush()
    t.stop()
    assert len(errors) == 1


def test_probe_success_after_stop_does_not_fire_reconnect():
    """stop() joins the daemon prober with only a 0.2s timeout, so a
    probe can complete mid-dispose; _came_back must then NOT invoke the
    reconnect hook on the already-disposed instance."""
    fired = []
    t = SyncTransport(Config(), on_receive=lambda *a: None,
                      on_reconnect=lambda: fired.append(1))
    with t._probe_lock:
        t._offline = True
    t.stop()  # sets _probe_stop; a straggler probe may land after this
    t._came_back()
    assert fired == []
    assert t._offline  # untouched: no half-applied transition

    # The pre-stop path still fires.
    t2 = SyncTransport(Config(), on_receive=lambda *a: None,
                       on_reconnect=lambda: fired.append(1))
    with t2._probe_lock:
        t2._offline = True
    t2._came_back()
    assert fired == [1]
    t2.stop()


def test_s2k_salted_and_simple_types():
    """Accept S2K types 0/1 per RFC 4880 (OpenPGP.js may emit them for
    other configs); our own output stays type 3."""
    import hashlib
    from evolu_tpu.sync import crypto

    pt = b"payload"
    ct = bytearray(crypto.encrypt_symmetric(pt, "pw"))
    # Rewrite the SKESK (first packet) from iterated (type 3) to salted
    # (type 1) with a matching manually-derived key... instead, build a
    # type-1 message directly: reuse internals.
    salt = bytes(range(8))
    key = hashlib.sha256(salt + b"pw").digest()
    skesk = crypto._new_packet(3, bytes([4, crypto.SYM_AES256, 1, crypto.HASH_SHA256]) + salt)
    import os as _os
    literal = crypto._new_packet(11, b"b\x00\x00\x00\x00\x00" + pt)
    prefix = _os.urandom(16)
    body = prefix + prefix[14:16] + literal
    mdc = hashlib.sha1(body + b"\xd3\x14").digest()
    enc = crypto._aes_cfb(key).encryptor()
    seipd = crypto._new_packet(18, b"\x01" + enc.update(body + b"\xd3\x14" + mdc) + enc.finalize())
    assert crypto.decrypt_symmetric(skesk + seipd, "pw") == pt

    # Type 0 (simple): key = sha256(password), no salt in the SKESK.
    key0 = hashlib.sha256(b"pw").digest()
    skesk0 = crypto._new_packet(3, bytes([4, crypto.SYM_AES256, 0, crypto.HASH_SHA256]))
    enc0 = crypto._aes_cfb(key0).encryptor()
    seipd0 = crypto._new_packet(18, b"\x01" + enc0.update(body + b"\xd3\x14" + mdc) + enc0.finalize())
    assert crypto.decrypt_symmetric(skesk0 + seipd0, "pw") == pt

    # Both branches reject a non-SHA256 hash algorithm declaration.
    import pytest as _pytest
    bad = crypto._new_packet(3, bytes([4, crypto.SYM_AES256, 1, 2]) + salt)  # SHA-1
    with _pytest.raises(crypto.PgpError, match="S2K hash"):
        crypto.decrypt_symmetric(bad + seipd, "pw")


def test_periodic_sync_trigger(tmp_path):
    """config.sync_interval drives automatic pull rounds — the headless
    analog of the reference's load/online/focus triggers."""
    import time as _time

    from evolu_tpu.runtime.client import Evolu
    from evolu_tpu.server.relay import RelayServer, RelayStore
    from evolu_tpu.sync.client import connect
    from evolu_tpu.utils.config import Config

    server = RelayServer(RelayStore(str(tmp_path / "relay.db"))).start()
    try:
        cfg = Config(sync_url=server.url + "/", sync_interval=0.05)
        a = Evolu(db_path=str(tmp_path / "a.db"), config=cfg)
        a.update_db_schema({"todo": ("title",)})
        connect(a)
        b = Evolu(db_path=str(tmp_path / "b.db"), config=cfg, mnemonic=a.owner.mnemonic)
        b.update_db_schema({"todo": ("title",)})
        connect(b)

        a.create("todo", {"title": "auto"})
        deadline = _time.time() + 10
        while _time.time() < deadline:
            rows = b.db.exec('SELECT COUNT(*) FROM "__message"')
            if rows == [(3,)]:
                break
            _time.sleep(0.05)
        assert b.db.exec('SELECT COUNT(*) FROM "__message"') == [(3,)]
        a.dispose(), b.dispose()
    finally:
        server.stop()


def test_get_messages_identical_across_backends():
    """The native packed reader and the Python query must return the
    same payloads (their SQL lives in two places — this pins them)."""
    from evolu_tpu.core.merkle import create_initial_merkle_tree
    from evolu_tpu.storage.native import native_available

    if not native_available():
        pytest.skip("native backend unavailable")
    stores = [RelayStore(backend="python"), RelayStore(backend="native")]
    own = TS  # requester's own node id suffix
    other = TS.replace("89e3b4f11a2c5d70", "0123456789abcdef")
    outs = []
    for store in stores:
        store.add_messages("u1", [_enc(own, b"mine"), _enc(other, b"\x00\xffblob")])
        tree = store.get_merkle_tree("u1")
        msgs = store.get_messages("u1", "89e3b4f11a2c5d70", tree, create_initial_merkle_tree())
        outs.append(msgs)
        store.close()
    assert outs[0] == outs[1]
    assert [m.timestamp for m in outs[0]] == [other]


def test_sync_wire_byte_identical_to_object_path():
    """`RelayStore.sync_wire` (one C call emitting the response
    messages stream, r4) must be BYTE-identical to
    encode_sync_response(store.sync(request)) across the three round
    shapes — cold pull, push, steady state — including NUL/0-length
    contents. Two stores replicate the same state so both paths see
    identical inputs."""
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.storage.native import native_available

    if not native_available():
        pytest.skip("native backend unavailable")
    msgs = tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(
                Timestamp(1_700_000_000_000 + i * 60_000, i % 4, "a1b2c3d4e5f60718")
            ),
            bytes([i % 256]) * (i % 50) + b"\x00\xfe" if i % 3 else b"",
        )
        for i in range(120)
    )
    a, b = RelayStore(), RelayStore()
    try:
        for s in (a, b):
            s.add_messages("u1", msgs)
        cold = protocol.SyncRequest((), "u1", "e" * 16, "{}")
        pure = protocol.encode_sync_response(a.sync(cold))
        wire = b.sync_wire(cold)
        assert wire == pure

        push = protocol.SyncRequest(msgs[:5], "u2", "f" * 16, "{}")
        assert b.sync_wire(push) == protocol.encode_sync_response(a.sync(push))

        steady = protocol.SyncRequest(
            (), "u1", "e" * 16, protocol.decode_sync_response(pure).merkle_tree
        )
        assert b.sync_wire(steady) == protocol.encode_sync_response(a.sync(steady))

        # NUL-bearing wire ids must bind with explicit lengths (r4: the
        # char* form truncated 'u\x00evil' to 'u', serving another
        # owner's rows on the native backend only).
        nul = protocol.SyncRequest(msgs[:2], "u\x00evil", "n\x00" + "f" * 14, "{}")
        assert b.sync_wire(nul) == protocol.encode_sync_response(a.sync(nul))
        # The fused CLIENT decoder consumes the fused SERVER bytes:
        # these contents aren't real OpenPGP, so every row demotes and
        # the oracle's PgpError surfaces — which proves the wire LAYER
        # itself parsed cleanly end to end (a wire rejection would
        # return None instead of raising).
        from evolu_tpu.sync import native_crypto
        from evolu_tpu.sync.crypto import PgpError

        if native_crypto.native_available():
            with pytest.raises(PgpError):
                native_crypto.decrypt_response(wire, "x")
    finally:
        a.close(), b.close()


def test_malformed_stored_timestamp_degrades_not_wedges():
    """A stored relay timestamp that is not the canonical 46-byte width
    breaks the packed C fetch paths (rc 2). That must DEGRADE the
    owner's sync to the generic SQL path — same rows as the pure-Python
    backend — not wedge every subsequent sync with an HTTP 500
    (advisor r4: sync_wire raised UnknownError)."""
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.storage.native import native_available

    if not native_available():
        pytest.skip("native backend unavailable")
    msgs = tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(
                Timestamp(1_700_000_000_000 + i * 60_000, 0, "a1b2c3d4e5f60718")
            ),
            b"c%d" % i,
        )
        for i in range(5)
    )
    native, pure = RelayStore(), RelayStore(backend="python")
    try:
        for s in (native, pure):
            s.add_messages("u1", msgs)
            # A malformed row can only enter via external corruption —
            # add_messages parses strictly — so inject it directly.
            s.db.run(
                'INSERT INTO "message" ("timestamp", "userId", "content") '
                "VALUES (?, ?, ?)",
                ("2099-01-01T00:00:00.000Z-00ff", "u1", b"bad"),
            )
        cold = protocol.SyncRequest((), "u1", "e" * 16, "{}")
        # sync_wire falls back to the object path (None), not a raise...
        assert native.sync_wire(cold) is None
        # ...and the object path serves the SAME rows as the pure
        # backend (generic-SQL fallback inside get_messages).
        got = native.sync(cold)
        want = pure.sync(cold)
        assert got.messages == want.messages
        assert {m.timestamp for m in got.messages} >= {m.timestamp for m in msgs}
    finally:
        native.close(), pure.close()


def test_merkle_tree_string_verbatim_and_respond_reuse():
    """`get_merkle_tree_string` must return the STORED text verbatim
    (the respond path serves it without a parse→re-dump round trip —
    r4), equal to re-serializing the parsed tree; empty owner → '{}'.
    And the engine's cold-sync response tree must be byte-identical
    whether or not the owner was touched this batch."""
    from evolu_tpu.core.merkle import merkle_tree_to_string
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.sync import protocol as proto

    store = RelayStore()
    try:
        store.add_messages("u1", [_enc(TS, b"x")])
        raw = store.get_merkle_tree_string("u1")
        assert raw == merkle_tree_to_string(store.get_merkle_tree("u1"))
        assert store.get_merkle_tree_string("nobody") == "{}"

        eng = BatchReconciler(store)
        cold = proto.SyncRequest((), "u1", "e" * 16, "{}")
        (resp,) = eng._respond([cold], {})  # untouched owner → raw path
        assert resp.merkle_tree == raw
        assert [m.timestamp for m in resp.messages] == [TS]
        eng.close()
    finally:
        store.close()


def test_relay_rejects_oversized_body(tmp_path):
    """20 MB body limit parity (index.ts:222): 413, no state change."""
    import urllib.error
    import urllib.request

    server = RelayServer(RelayStore(str(tmp_path / "r.db"))).start()
    try:
        req = urllib.request.Request(
            server.url + "/", data=b"", method="POST",
            headers={"Content-Length": str(21 * 1024 * 1024)},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 413
        assert server.store.db.exec('SELECT COUNT(*) FROM "message"') == [(0,)]
    finally:
        server.stop()


# --- strict interop mode (Config.wire_extensions=False) ---


def test_strict_mode_refuses_extension_values():
    """With extensions off, values outside the reference's string|int32
    oneof (protobuf.proto:5-13) refuse at MUTATION time — before they
    enter the log — so sync can never wedge on an unencodable resend.
    The encoder primitive enforces the same gate."""
    for v in (3.25, -1e300, 2**31, -(2**31) - 1, 2**62):
        with pytest.raises(TypeError):
            protocol.encode_content("t", "r", "c", v, extensions=False)
        with pytest.raises(TypeError):
            protocol.assert_wire_encodable(v, extensions=False)
    # With extensions, float/int64 pass the gate but bytes (which the
    # wire can NEVER express, though SQLite stores them happily) and
    # beyond-int64 ints are refused even in the default mode.
    for v in (3.25, 2**62, -(2**31) - 1):
        protocol.assert_wire_encodable(v, extensions=True)
    for v in (b"blob", 2**64, object()):
        with pytest.raises(TypeError):
            protocol.assert_wire_encodable(v, extensions=True)

    evolu = create_evolu(
        {"todo": ("title", "n")},
        config=Config(wire_extensions=False, reconnect_probe_interval=None),
    )
    try:
        errors = []
        evolu.subscribe_error(errors.append)
        sends = []
        evolu.worker.post_sync = lambda r: sends.append(r)
        evolu.create("todo", {"title": "ok", "n": 3.25})
        evolu.worker.flush()
        assert errors and "string|int32" in str(errors[0])
        # The WHOLE command rolled back: no poison in the log, nothing
        # pushed, and the owner keeps syncing afterwards.
        assert evolu.db.exec('SELECT count(*) FROM "__message"') == [(0,)]
        assert not sends
        evolu.create("todo", {"title": "fine", "n": 7})
        evolu.worker.flush()
        assert sends and len(evolu.db.exec('SELECT * FROM "todo"')) == 1
    finally:
        evolu.dispose()


def test_strict_mode_relays_remote_extension_values_verbatim():
    """A strict replica that RECEIVED a float from a lax peer must still
    be able to push it onward (relay semantics): the transport encodes
    with extensions allowed; strictness gates only local authoring."""
    from evolu_tpu.core.types import CrdtMessage

    msgs = (CrdtMessage(TS, "todo", "r", "n", 3.25),)
    encrypted = encrypt_messages(msgs, "any mnemonic")
    got = decrypt_messages(encrypted, "any mnemonic")
    assert got[0].value == 3.25


def test_strict_mode_reference_range_bytes_identical():
    """Reference-range traffic must be byte-identical with the flag on
    or off — strict mode only REJECTS, it never re-encodes. The protoc
    golden fixture (test_sync_request_golden_fixture) pins this same
    canonical form."""
    for v in ("hello", "", "ünïcode ✓", 0, 1, -1, 2**31 - 1, -(2**31), None, True, False):
        strict = protocol.encode_content("todo", "r1", "c1", v, extensions=False)
        lax = protocol.encode_content("todo", "r1", "c1", v, extensions=True)
        assert strict == lax


# --- lax-wire interop corner: a FLOAT written into the reference's
# int32 `numberValue` field (VERDICT #5). The reference client encodes
# with protobuf-ts (SURVEY.md:263); its `varint32write(value, buf)`
# applies JS BITWISE ops to the raw number — `value & 0x7f` /
# `value >> 7` truncate through ToInt32 — and the final sub-0x80 chunk
# is pushed as-is and truncated by the Uint8Array store (ToUint8). Net
# effect: the wire carries the varint of trunc(value); the fraction
# NEVER reaches the wire, so there is no "float in an int32 field" to
# detect — only a well-formed int32 varint. (protobuf-ts's debug
# `assertInt32` would throw first in dev builds; the production
# minified path and protobufjs-lineage writers share the truncating
# arithmetic. Either way the only bytes a peer can emit for the field
# are integer varints.)
#
# Pinned decision: our decoder treats field 5 as what the wire says —
# the truncated int32 — with the same |0 wrap every conformant decoder
# applies. No new error surface (the ValueError-only contract is for
# MALFORMED wire; these fixtures are well-formed), and re-encoding the
# decoded value is byte-stable, so relaying never rewrites it.


def _content_with_field5(varint_bytes: bytes) -> bytes:
    # table=1 "t", row=2 "r", column=3 "c", then field 5 (tag 0x28,
    # varint) with the hand-built payload protobuf-ts would emit.
    return (
        b"\x0a\x01t" + b"\x12\x01r" + b"\x1a\x01c" + b"\x28" + varint_bytes
    )


@pytest.mark.parametrize(
    "varint_bytes, expected",
    [
        # 3.5 → final chunk push(3.5), Uint8Array stores 3.
        (b"\x03", 3),
        # 300.7 → (300.7 & 0x7f)|0x80 = 0xac, 300.7 >>> 7 = 2.
        (b"\xac\x02", 300),
        # -2.5 → negative branch: 9 × (value & 127 | 128) with ToInt32
        # truncation (-2), then push(1) — the 10-byte two's-complement
        # varint of -2.
        (b"\xfe" + b"\xff" * 8 + b"\x01", -2),
        # 2^31 + 0.5 → bitwise ops wrap to int32: decodes as -2^31.
        (b"\x80\x80\x80\x80\x08", -(2**31)),
    ],
)
def test_protobuf_ts_float_in_int32_field_fixture(varint_bytes, expected):
    table, row, column, value = protocol.decode_content(
        _content_with_field5(varint_bytes)
    )
    assert (table, row, column) == ("t", "r", "c")
    assert value == expected and isinstance(value, int)
    # Relay stability: re-encoding the decoded value reproduces the
    # canonical field-5 varint (no silent rewrite into the float
    # extension field).
    assert protocol.encode_content("t", "r", "c", value) == _content_with_field5(
        protocol._varint(expected)
    )


def test_our_encoder_never_emits_field5_for_floats():
    """The converse pin: OUR encoder routes non-integer numbers to the
    doubleValue=6 extension (or raises in strict interop mode) — a
    float can never masquerade as an int32 on our side of the wire."""
    data = protocol.encode_content("t", "r", "c", 3.5)
    assert b"\x28" not in data.split(b"\x1a\x01c")[1][:1]  # no field-5 tag after column
    assert protocol.decode_content(data)[3] == 3.5
    with pytest.raises(TypeError):
        protocol.encode_content("t", "r", "c", 3.5, extensions=False)


def test_capability_extension_codec_and_v1_byte_identity():
    """ISSUE 7: the capability extension (SyncRequest field 5 /
    SyncResponse field 3) round-trips, is bounded, and — crucially —
    the capability-LESS wire is byte-for-byte the v1 wire, so a
    reference peer and every pre-extension fixture stay untouched."""
    req = protocol.SyncRequest((), "uid", "node", "{}")
    b0 = protocol.encode_sync_request(req)
    # No capabilities => no field 5 anywhere (v1 bytes).
    assert protocol.encode_request_capabilities(()) == b""
    assert protocol.decode_sync_request(b0).capabilities == ()
    caps = (protocol.CAP_CRDT_TYPES, protocol.CAP_CRDT_LIST,
            protocol.CAP_CRDT_TENSOR, "future-cap")
    b1 = protocol.encode_sync_request(
        protocol.SyncRequest((), "uid", "node", "{}", caps))
    assert b1 == b0 + protocol.encode_request_capabilities(caps)
    assert protocol.decode_sync_request(b1).capabilities == caps
    # Appending to an externally-encoded body (the fused C path's
    # route) decodes identically.
    assert protocol.decode_sync_request(
        b0 + protocol.encode_request_capabilities(caps)).capabilities == caps

    resp = protocol.SyncResponse((), '{"t":1}')
    r0 = protocol.encode_sync_response(resp)
    r1 = protocol.encode_sync_response(
        protocol.SyncResponse((), '{"t":1}', (protocol.CAP_CRDT_TYPES,)))
    assert r1 == r0 + protocol.encode_response_capabilities(
        (protocol.CAP_CRDT_TYPES,))
    assert protocol.decode_sync_response(r0).capabilities == ()
    assert protocol.scan_sync_response_capabilities(r0) == ()
    assert protocol.scan_sync_response_capabilities(r1) == (
        protocol.CAP_CRDT_TYPES,)
    # Decode bound: a hostile body cannot mint unbounded strings.
    flood = r0 + protocol.encode_response_capabilities(("x",) * 65)
    with pytest.raises(ValueError):
        protocol.decode_sync_response(flood)
    with pytest.raises(ValueError):
        protocol.scan_sync_response_capabilities(flood)
    # Wire-type abuse stays ValueError (the decorator contract).
    with pytest.raises(ValueError):
        protocol.decode_sync_request(b0 + b"\x28\x05")  # field 5 as varint


def test_capability_negotiation_v1_relay_fallback():
    """An unknown-capability (v1) relay answers an advertising client
    byte-identically to a capability-less exchange; a current relay
    echoes the intersection appended AFTER the v1 response bytes."""
    import urllib.request

    from evolu_tpu.server.relay import RelayServer, RelayStore

    def post(url, body):
        r = urllib.request.urlopen(
            urllib.request.Request(url, data=body, method="POST"))
        return r.read()

    body = protocol.encode_sync_request(
        protocol.SyncRequest((), "ownerX", "node", "{}"))
    adv = body + protocol.encode_request_capabilities(
        (protocol.CAP_CRDT_TYPES, protocol.CAP_CRDT_LIST,
         protocol.CAP_CRDT_TENSOR, "not-a-real-cap"))
    current = RelayServer(RelayStore()).start()
    v1 = RelayServer(RelayStore(), capabilities=()).start()
    try:
        plain = post(current.url, body)
        assert protocol.scan_sync_response_capabilities(plain) == ()
        negotiated = post(current.url, adv)
        assert negotiated == plain + protocol.encode_response_capabilities(
            (protocol.CAP_CRDT_TYPES, protocol.CAP_CRDT_LIST,
             protocol.CAP_CRDT_TENSOR))
        assert post(v1.url, adv) == plain  # v1 fallback: byte-identical
    finally:
        current.stop()
        v1.stop()
