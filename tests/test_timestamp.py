"""HLC golden tests.

Expected values are ported from the reference's vitest snapshots
(packages/evolu/test/timestamp.test.ts +
test/__snapshots__/timestamp.test.ts.snap) — byte-for-byte parity with
the TypeScript implementation is the contract.
"""

import pytest

from evolu_tpu.core.timestamp import (
    create_initial_timestamp,
    create_sync_timestamp,
    receive_timestamp,
    send_timestamp,
    timestamp_from_string,
    timestamp_to_hash,
    timestamp_to_string,
)
from evolu_tpu.core.types import (
    Timestamp,
    TimestampCounterOverflowError,
    TimestampDriftError,
    TimestampDuplicateNodeError,
)

MAX_DRIFT = 60000


def node1(millis=0, counter=0):
    return Timestamp(millis, counter, "0000000000000001")


def node2(millis=0, counter=0):
    return Timestamp(millis, counter, "0000000000000002")


def test_create_initial_timestamp():
    ts = create_initial_timestamp()
    assert ts.counter == 0
    assert ts.millis == 0
    assert len(ts.node) == 16


def test_create_sync_timestamp():
    ts = create_sync_timestamp()
    assert (ts.millis, ts.counter, ts.node) == (0, 0, "0000000000000000")


def test_timestamp_to_string():
    # snapshot `timestampToString 1`
    assert (
        timestamp_to_string(create_sync_timestamp())
        == "1970-01-01T00:00:00.000Z-0000-0000000000000000"
    )


def test_timestamp_string_roundtrip():
    t = create_sync_timestamp()
    assert timestamp_from_string(timestamp_to_string(t)) == t
    t2 = Timestamp(1656873738591, 42, "a1b2c3d4e5f60718")
    assert timestamp_from_string(timestamp_to_string(t2)) == t2


def test_timestamp_string_order_is_tuple_order():
    ts = [
        Timestamp(0, 0, "0000000000000001"),
        Timestamp(0, 1, "0000000000000000"),
        Timestamp(1, 0, "ffffffffffffffff"),
        Timestamp(1656873738591, 65535, "0000000000000000"),
        Timestamp(1656873738591, 65535, "0000000000000001"),
        Timestamp(1656873738592, 0, "0000000000000000"),
    ]
    strings = [timestamp_to_string(t) for t in ts]
    assert strings == sorted(strings)


def test_timestamp_to_hash():
    # snapshot `timestampToHash 1`
    assert timestamp_to_hash(create_sync_timestamp()) == 4179357717


class TestSendTimestamp:
    def test_monotonic_clock(self):
        # snapshot: millis 1, counter 0
        t = send_timestamp(create_sync_timestamp(), now=1)
        assert (t.millis, t.counter, t.node) == (1, 0, "0000000000000000")

    def test_stuttering_clock(self):
        # snapshot: millis 0, counter 1
        t = send_timestamp(create_sync_timestamp(), now=0)
        assert (t.millis, t.counter, t.node) == (0, 1, "0000000000000000")

    def test_regressing_clock(self):
        # snapshot: millis 1, counter 1
        t = send_timestamp(create_sync_timestamp(1), now=0)
        assert (t.millis, t.counter, t.node) == (1, 1, "0000000000000000")

    def test_counter_overflow(self):
        t = create_sync_timestamp()
        with pytest.raises(TimestampCounterOverflowError):
            for _ in range(65536):
                t = send_timestamp(t, now=0)

    def test_clock_drift(self):
        with pytest.raises(TimestampDriftError) as e:
            send_timestamp(create_sync_timestamp(MAX_DRIFT + 1), now=0)
        assert e.value.next == 60001
        assert e.value.now == 0


class TestReceiveTimestamp:
    def test_wall_clock_later_than_both(self):
        t = receive_timestamp(node1(), node2(0, 0), now=1)
        assert (t.millis, t.counter, t.node) == (1, 0, "0000000000000001")

    def test_same_millis_take_bigger_counter(self):
        t = receive_timestamp(node1(1, 0), node2(1, 1), now=0)
        assert (t.millis, t.counter, t.node) == (1, 2, "0000000000000001")
        t = receive_timestamp(node1(1, 1), node2(1, 0), now=0)
        assert (t.millis, t.counter, t.node) == (1, 2, "0000000000000001")

    def test_local_millis_later(self):
        t = receive_timestamp(node1(2), node2(1), now=0)
        assert (t.millis, t.counter, t.node) == (2, 1, "0000000000000001")

    def test_remote_millis_later(self):
        t = receive_timestamp(node1(1), node2(2), now=0)
        assert (t.millis, t.counter, t.node) == (2, 1, "0000000000000001")

    def test_duplicate_node(self):
        with pytest.raises(TimestampDuplicateNodeError) as e:
            receive_timestamp(node1(), node1(), now=1)
        assert e.value.node == "0000000000000001"

    def test_clock_drift(self):
        with pytest.raises(TimestampDriftError) as e:
            receive_timestamp(create_sync_timestamp(MAX_DRIFT + 1), node2(), now=0)
        assert (e.value.next, e.value.now) == (60001, 0)
        with pytest.raises(TimestampDriftError):
            receive_timestamp(node2(), create_sync_timestamp(MAX_DRIFT + 1), now=0)

    def test_drift_checked_before_duplicate_node(self):
        # The reference checks drift first (timestamp.ts:138-153).
        with pytest.raises(TimestampDriftError):
            receive_timestamp(
                node1(MAX_DRIFT + 1), node1(MAX_DRIFT + 1), now=0
            )
