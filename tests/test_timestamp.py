"""HLC golden tests.

Expected values are ported from the reference's vitest snapshots
(packages/evolu/test/timestamp.test.ts +
test/__snapshots__/timestamp.test.ts.snap) — byte-for-byte parity with
the TypeScript implementation is the contract.
"""

import pytest

from evolu_tpu.core.timestamp import (
    create_initial_timestamp,
    create_sync_timestamp,
    receive_timestamp,
    send_timestamp,
    timestamp_from_string,
    timestamp_to_hash,
    timestamp_to_string,
)
from evolu_tpu.core.types import (
    Timestamp,
    TimestampCounterOverflowError,
    TimestampDriftError,
    TimestampDuplicateNodeError,
)

MAX_DRIFT = 60000


def node1(millis=0, counter=0):
    return Timestamp(millis, counter, "0000000000000001")


def node2(millis=0, counter=0):
    return Timestamp(millis, counter, "0000000000000002")


def test_create_initial_timestamp():
    ts = create_initial_timestamp()
    assert ts.counter == 0
    assert ts.millis == 0
    assert len(ts.node) == 16


def test_create_sync_timestamp():
    ts = create_sync_timestamp()
    assert (ts.millis, ts.counter, ts.node) == (0, 0, "0000000000000000")


def test_timestamp_to_string():
    # snapshot `timestampToString 1`
    assert (
        timestamp_to_string(create_sync_timestamp())
        == "1970-01-01T00:00:00.000Z-0000-0000000000000000"
    )


def test_timestamp_string_roundtrip():
    t = create_sync_timestamp()
    assert timestamp_from_string(timestamp_to_string(t)) == t
    t2 = Timestamp(1656873738591, 42, "a1b2c3d4e5f60718")
    assert timestamp_from_string(timestamp_to_string(t2)) == t2


def test_timestamp_string_order_is_tuple_order():
    ts = [
        Timestamp(0, 0, "0000000000000001"),
        Timestamp(0, 1, "0000000000000000"),
        Timestamp(1, 0, "ffffffffffffffff"),
        Timestamp(1656873738591, 65535, "0000000000000000"),
        Timestamp(1656873738591, 65535, "0000000000000001"),
        Timestamp(1656873738592, 0, "0000000000000000"),
    ]
    strings = [timestamp_to_string(t) for t in ts]
    assert strings == sorted(strings)


def test_timestamp_to_hash():
    # snapshot `timestampToHash 1`
    assert timestamp_to_hash(create_sync_timestamp()) == 4179357717


class TestSendTimestamp:
    def test_monotonic_clock(self):
        # snapshot: millis 1, counter 0
        t = send_timestamp(create_sync_timestamp(), now=1)
        assert (t.millis, t.counter, t.node) == (1, 0, "0000000000000000")

    def test_stuttering_clock(self):
        # snapshot: millis 0, counter 1
        t = send_timestamp(create_sync_timestamp(), now=0)
        assert (t.millis, t.counter, t.node) == (0, 1, "0000000000000000")

    def test_regressing_clock(self):
        # snapshot: millis 1, counter 1
        t = send_timestamp(create_sync_timestamp(1), now=0)
        assert (t.millis, t.counter, t.node) == (1, 1, "0000000000000000")

    def test_counter_overflow(self):
        t = create_sync_timestamp()
        with pytest.raises(TimestampCounterOverflowError):
            for _ in range(65536):
                t = send_timestamp(t, now=0)

    def test_clock_drift(self):
        with pytest.raises(TimestampDriftError) as e:
            send_timestamp(create_sync_timestamp(MAX_DRIFT + 1), now=0)
        assert e.value.next == 60001
        assert e.value.now == 0


class TestReceiveTimestamp:
    def test_wall_clock_later_than_both(self):
        t = receive_timestamp(node1(), node2(0, 0), now=1)
        assert (t.millis, t.counter, t.node) == (1, 0, "0000000000000001")

    def test_same_millis_take_bigger_counter(self):
        t = receive_timestamp(node1(1, 0), node2(1, 1), now=0)
        assert (t.millis, t.counter, t.node) == (1, 2, "0000000000000001")
        t = receive_timestamp(node1(1, 1), node2(1, 0), now=0)
        assert (t.millis, t.counter, t.node) == (1, 2, "0000000000000001")

    def test_local_millis_later(self):
        t = receive_timestamp(node1(2), node2(1), now=0)
        assert (t.millis, t.counter, t.node) == (2, 1, "0000000000000001")

    def test_remote_millis_later(self):
        t = receive_timestamp(node1(1), node2(2), now=0)
        assert (t.millis, t.counter, t.node) == (2, 1, "0000000000000001")

    def test_duplicate_node(self):
        with pytest.raises(TimestampDuplicateNodeError) as e:
            receive_timestamp(node1(), node1(), now=1)
        assert e.value.node == "0000000000000001"

    def test_clock_drift(self):
        with pytest.raises(TimestampDriftError) as e:
            receive_timestamp(create_sync_timestamp(MAX_DRIFT + 1), node2(), now=0)
        assert (e.value.next, e.value.now) == (60001, 0)
        with pytest.raises(TimestampDriftError):
            receive_timestamp(node2(), create_sync_timestamp(MAX_DRIFT + 1), now=0)

    def test_drift_checked_before_duplicate_node(self):
        # The reference checks drift first (timestamp.ts:138-153).
        with pytest.raises(TimestampDriftError):
            receive_timestamp(
                node1(MAX_DRIFT + 1), node1(MAX_DRIFT + 1), now=0
            )


def test_receive_batch_reduction_matches_sequential_fold():
    """The vectorized receive fold (SURVEY §7 "HLC receive is
    reducible") must equal the sequential fold on adversarial batches:
    frozen clocks, millis ties with local and remotes, counter chains,
    regressing remote order."""
    import random as _random

    import numpy as np

    from evolu_tpu.core.timestamp import (
        Timestamp,
        receive_timestamp,
        receive_timestamps_batch,
    )

    rng = _random.Random(42)
    base = 1_700_000_000_000
    for trial in range(200):
        n = rng.randrange(1, 40)
        local = Timestamp(base + rng.randrange(0, 5), rng.randrange(0, 5), "a" * 16)
        now = base + rng.randrange(0, 8)
        millis = np.array(
            [base + rng.randrange(0, 8) for _ in range(n)], np.int64
        )
        counter = np.array([rng.randrange(0, 7) for _ in range(n)], np.int64)
        nodes = [f"{rng.randrange(1, 6):016x}" for _ in range(n)]

        expect = local
        err = None
        try:
            for i in range(n):
                expect = receive_timestamp(
                    expect, Timestamp(int(millis[i]), int(counter[i]), nodes[i]), now
                )
        except Exception as e:  # noqa: BLE001
            err = e

        if err is None:
            got = receive_timestamps_batch(local, millis, counter, nodes, now)
            assert (got.millis, got.counter, got.node) == (
                expect.millis, expect.counter, expect.node,
            ), trial
        else:
            import pytest as _pytest

            with _pytest.raises(type(err)):
                receive_timestamps_batch(local, millis, counter, nodes, now)

    # Adversarial regime: drift-range millis, node collisions with the
    # local clock, counters near the overflow boundary — every error
    # branch must reproduce the sequential fold's error type.
    error_types = set()
    for trial in range(200):
        n = rng.randrange(1, 30)
        local = Timestamp(base, rng.randrange(65_500, 65_536), "a" * 16)
        now = base + rng.randrange(0, 3)
        millis = np.array(
            [base + rng.choice([0, 1, 59_999, 60_004, 120_000]) for _ in range(n)],
            np.int64,
        )
        counter = np.array(
            [rng.choice([0, 65_530, 65_535]) for _ in range(n)], np.int64
        )
        nodes = [rng.choice(["a" * 16, "b" * 16]) for _ in range(n)]
        expect = local
        err = None
        try:
            for i in range(n):
                expect = receive_timestamp(
                    expect, Timestamp(int(millis[i]), int(counter[i]), nodes[i]), now
                )
        except Exception as e:  # noqa: BLE001
            err = e
        import pytest as _pytest

        if err is None:
            got = receive_timestamps_batch(local, millis, counter, nodes, now)
            assert (got.millis, got.counter, got.node) == (
                expect.millis, expect.counter, expect.node,
            ), trial
        else:
            error_types.add(type(err).__name__)
            with _pytest.raises(type(err)):
                receive_timestamps_batch(local, millis, counter, nodes, now)
    # The adversarial regime must actually exercise error paths.
    assert error_types, "adversarial fuzz never errored"


def test_receive_batch_error_parity():
    """Error type/payload parity on the fallback path: drift, duplicate
    node, and mid-run counter overflow (which a final-state-only check
    would miss)."""
    import numpy as np
    import pytest as _pytest

    from evolu_tpu.core.timestamp import (
        Timestamp,
        TimestampCounterOverflowError,
        TimestampDriftError,
        TimestampDuplicateNodeError,
        receive_timestamps_batch,
    )

    base = 1_700_000_000_000
    local = Timestamp(base, 0, "a" * 16)

    with _pytest.raises(TimestampDriftError):
        receive_timestamps_batch(
            local, np.array([base + 120_000]), np.array([0]), ["b" * 16], now=base
        )
    with _pytest.raises(TimestampDuplicateNodeError):
        receive_timestamps_batch(
            local, np.array([base]), np.array([0]), ["a" * 16], now=base
        )
    # 65536 frozen-clock receives overflow the counter mid-run even
    # though a later message with larger millis would reset it.
    n = 65_536
    millis = np.full(n + 1, base, np.int64)
    millis[-1] = base + 1
    counter = np.zeros(n + 1, np.int64)
    nodes = ["b" * 16] * (n + 1)
    with _pytest.raises(TimestampCounterOverflowError):
        receive_timestamps_batch(local, millis, counter, nodes, now=base)


def test_receive_batch_node_compare_is_case_sensitive():
    """Non-canonical uppercase wire hex for the same node value must NOT
    trigger the duplicate-node error — the reference compares strings."""
    import numpy as np

    from evolu_tpu.core.timestamp import Timestamp, receive_timestamps_batch

    base = 1_700_000_000_000
    local = Timestamp(base, 0, "00000000000000ab")
    got = receive_timestamps_batch(
        local, np.array([base], np.int64), np.array([3], np.int64),
        ["00000000000000AB"], now=base,
    )
    assert got.counter == 4 and got.node == local.node


def test_receive_batch_large_distinct_millis_stays_vectorized():
    """100k messages with distinct millis cannot overflow (every step
    resets the counter), so the fold must NOT fall back to the
    sequential per-message path."""
    import numpy as np

    import evolu_tpu.core.timestamp as ts_mod
    from evolu_tpu.core.timestamp import Timestamp, receive_timestamps_batch

    base = 1_700_000_000_000
    # n > 65535 (the counter range): a whole-batch `+ n` overflow bound
    # would wrongly fall back; the run-length bound must not. Millis
    # rise every second message, so the longest flat run is 1 and the
    # span (n/2) stays inside max_drift of `now`.
    n = 100_000
    millis = base + 1 + np.arange(n, dtype=np.int64) // 2
    counter = np.zeros(n, np.int64)
    nodes = ["b" * 16] * n

    calls = []
    orig = ts_mod.receive_timestamp
    ts_mod.receive_timestamp = lambda *a, **k: calls.append(1) or orig(*a, **k)
    try:
        got = receive_timestamps_batch(
            Timestamp(base, 7, "a" * 16), millis, counter, nodes, now=base
        )
    finally:
        ts_mod.receive_timestamp = orig
    assert not calls, "large clean batch fell back to the sequential fold"
    assert got.millis == base + n // 2
    # Final millis arrives via a remote tie (counter = 0 + 1), then its
    # duplicate ties with the local clock (max(1, 0) + 1 = 2).
    assert got.counter == 2


def test_parse_rejects_per_string_length_tricks():
    import pytest as _pytest

    from evolu_tpu.core.types import TimestampParseError
    from evolu_tpu.ops.host_parse import parse_timestamp_strings

    good = "2024-01-15T10:30:00.123Z-0001-89e3b4f11a2c5d70"
    with _pytest.raises(TimestampParseError):
        parse_timestamp_strings(["", good + good])  # joined length still n*46
