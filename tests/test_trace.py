"""Distributed tracing (evolu_tpu/obs/trace.py, ISSUE 10): context
codec + deterministic sampling, the bounded span ring and fan-in link
retrieval, chrome export shape, the relay's GET /trace surface and its
optional token gate, traceparent header fuzz (malformed headers are
ignored, never a 4xx/5xx), the client transport's header hop, and the
acceptance scenario — a 2-relay fleet driving one client mutation
through routing → forward → scheduler-coalesce → engine → gossip with
a SINGLE trace id yielding a span tree covering every hop on both
relays, while wire bytes (v1 and v2 records alike) and SQLite end
state stay byte-identical with tracing on."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import metrics, trace
from evolu_tpu.server.relay import RelayServer, RelayStore, serve_single_request
from evolu_tpu.server.scheduler import SyncScheduler
from evolu_tpu.sync import aead, protocol
from evolu_tpu.sync.client import _http_post
from evolu_tpu.utils.config import FleetConfig
from evolu_tpu.utils.log import logger

BASE = 1_700_000_000_000


@pytest.fixture(autouse=True)
def _clean_slate():
    logger.clear()  # resets metrics + flight + trace ring
    trace.set_enabled(True)
    trace.set_sample_rate(1.0)
    yield
    trace.set_enabled(True)
    trace.set_sample_rate(1.0)
    logger.clear()


def _msgs(k, n, t0=0, content=b"ct-%d"):
    node = f"{k + 1:016x}"
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (t0 + j) * 1000, 0, node)),
            content % (t0 + j) if b"%d" in content else content,
        )
        for j in range(n)
    )


def _sync_request(owner, messages=(), tree="{}"):
    return protocol.SyncRequest(messages, owner, "00000000000000bb", tree)


def _owner_for(ring, url, prefix="o"):
    i = 0
    while True:
        uid = f"{prefix}{i:04d}"
        if ring.primary(uid) == url.rstrip("/"):
            return uid
        i += 1


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.read()


# --- context codec + sampling ---


def test_traceparent_roundtrip():
    ctx = trace.SpanContext("ab" * 16, "cd" * 8, True)
    assert trace.format_traceparent(ctx) == f"00-{'ab' * 16}-{'cd' * 8}-01"
    back = trace.parse_traceparent(trace.format_traceparent(ctx))
    assert back.trace_id == ctx.trace_id and back.span_id == ctx.span_id


@pytest.mark.parametrize("value", [
    None, "", "garbage", "00", "00-xyz", "00-" + "g" * 32 + "-" + "cd" * 8 + "-01",
    "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",      # all-zero trace id
    "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",     # all-zero span id
    "00-" + "ab" * 16 + "-" + "cd" * 8,             # missing flags
    "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01-xx",  # v00 with extra member
    "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",     # forbidden version
    "00-" + "AB" * 16 + "-" + "cd" * 8 + "-01",     # uppercase hex
    "x" * 10_000,                                   # oversized
    "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01" + "-m" * 500,
])
def test_parse_traceparent_never_raises_and_rejects(value):
    assert trace.parse_traceparent(value) is None


def test_parse_accepts_future_version_with_extra_members():
    ctx = trace.parse_traceparent(
        "01-" + "ab" * 16 + "-" + "cd" * 8 + "-01-extra-members"
    )
    assert ctx is not None and ctx.trace_id == "ab" * 16


def test_sampling_is_deterministic_and_proportional():
    rec = trace.TraceRecorder()
    rec.sample_rate = 0.5
    ids = [rec.new_trace_id() for _ in range(1000)]
    decisions = [rec.sampled(t) for t in ids]
    # Deterministic: same id, same answer, every time.
    assert decisions == [rec.sampled(t) for t in ids]
    assert 350 < sum(decisions) < 650  # ~50%, generous bounds
    rec.sample_rate = 1.0
    assert all(rec.sampled(t) for t in ids)
    rec.sample_rate = 0.0
    assert not any(rec.sampled(t) for t in ids)


def test_unsampled_trace_propagates_context_but_records_nothing():
    rec = trace.TraceRecorder()
    rec.sample_rate = 0.0
    s = rec.start_span("quiet")
    assert s.context is not None  # downstream hops still see the id
    # No exemplar may be minted from an unsampled span: the histogram→
    # trace jump must never dead-end on a trace the ring can't show.
    assert s.trace_id is None
    s.end()
    assert rec.dump() == []


def test_link_forced_span_promotes_its_context_so_children_record():
    """A fan-in span recorded because a LINKED trace is sampled must
    hand children (the engine pass's kernel:* spans) a sampled
    context — not silently drop them whenever its own fresh trace
    rolls unsampled."""
    rec = trace.TraceRecorder()
    rec.sample_rate = 1.0
    req = rec.start_span("request")
    req.end()
    rec.sample_rate = 0.0  # every fresh trace now rolls unsampled
    batch = rec.start_span("batch", links=[req.context])
    assert batch.context.sampled  # promoted
    child = rec.start_span("kernel:merkle", parent=batch.context)
    child.end()
    batch.end()
    names = {s.name for s in rec.dump()}
    assert {"request", "batch", "kernel:merkle"} <= names


# --- ring + links + exports ---


def test_span_ring_is_bounded():
    rec = trace.TraceRecorder(capacity=8)
    for i in range(50):
        rec.start_span(f"s{i}").end()
    assert len(rec.dump()) == 8


def test_spans_for_includes_fanin_links_and_tree_nests():
    root = trace.start_span("root")
    child = trace.start_span("child", parent=root.context)
    child.end()
    root.end()
    batch = trace.start_span("batch", links=[child.context])
    batch.end()
    got = trace.serve_trace(root.trace_id)
    names = {s["name"] for s in got["spans"]}
    assert names == {"root", "child", "batch"}
    (tree_root,) = [n for n in got["tree"] if n["name"] == "root"]
    assert [c["name"] for c in tree_root["children"]] == ["child"]
    (linked,) = [n for n in got["tree"] if n.get("linked")]
    assert linked["name"] == "batch"
    assert [root.trace_id, child.context.span_id] in linked["links"]


def test_chrome_export_shape():
    s = trace.start_span("kernel:merkle", attrs={"n": 3})
    s.end()
    out = trace.export_chrome()
    (ev,) = [e for e in out["traceEvents"] if e["name"] == "kernel:merkle"]
    assert ev["ph"] == "X" and ev["dur"] >= 0 and ev["args"]["n"] == 3


def test_log_span_mirrors_into_active_trace_under_kernel_name():
    from evolu_tpu.utils.log import span

    root = trace.start_span("batch")
    with trace.use(root.context):
        with span("kernel:reconcile"):
            pass
    root.end()
    names = [s.name for s in trace.spans_for(root.trace_id)]
    assert "kernel:reconcile" in names and "batch" in names


def test_write_evidence_artifact(tmp_path):
    trace.start_span("ev").end()
    path = trace.write_evidence("unit", seed=7)
    with open(path) as f:
        payload = json.load(f)
    assert payload["seed"] == 7
    assert any(e["name"] == "ev" for e in payload["trace"]["traceEvents"])
    assert "counters" in payload["metrics"]


# --- relay surface: /trace + token gate + header fuzz ---


def test_relay_trace_endpoint_and_404s():
    server = RelayServer(RelayStore()).start()
    try:
        root = trace.start_span("client.mutate")
        hdr = {trace.TRACEPARENT_HEADER: trace.format_traceparent(root.context)}
        _http_post(server.url + "/", protocol.encode_sync_request(
            _sync_request("alice", _msgs(0, 2))), headers=hdr)
        root.end()
        got = json.loads(_get(server.url + f"/trace/{root.trace_id}"))
        names = {s["name"] for s in got["spans"]}
        assert {"client.mutate", "relay.sync", "relay.respond"} <= names
        (srv,) = [s for s in got["spans"] if s["name"] == "relay.sync"]
        assert srv["trace_id"] == root.trace_id
        assert srv["attrs"]["owner"] == "alice"
        # The index lists the trace; chrome format parses.
        assert root.trace_id in json.loads(_get(server.url + "/trace"))["recent"]
        chrome = json.loads(_get(
            server.url + f"/trace/{root.trace_id}?format=chrome"))
        assert chrome["traceEvents"]
        # Not-a-trace-id answers 404, never 500.
        for bad in ("zz", "a" * 31, "A" * 32, "a" * 33):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + "/trace/" + bad)
            assert e.value.code == 404
    finally:
        server.stop()


def test_obs_token_gates_metrics_stats_and_trace(monkeypatch):
    server = RelayServer(RelayStore()).start()
    try:
        monkeypatch.setenv("EVOLU_OBS_TOKEN", "s3cret")
        for path in ("/metrics", "/stats", "/trace"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + path)
            assert e.value.code == 403
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + path, {"X-Evolu-Obs-Token": "wrong"})
            assert e.value.code == 403
            # A non-ASCII token header must 403, never crash the
            # handler (compare_digest rejects non-ASCII str inputs).
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + path, {"X-Evolu-Obs-Token": "s\xe9cret"})
            assert e.value.code == 403
            assert _get(server.url + path, {"X-Evolu-Obs-Token": "s3cret"})
        # /ping (liveness) stays open — probes carry no tokens.
        assert _get(server.url + "/ping") == b"ok"
        monkeypatch.delenv("EVOLU_OBS_TOKEN")
        assert _get(server.url + "/metrics")  # unset = open, unchanged
    finally:
        server.stop()


def test_malformed_traceparent_headers_are_ignored_never_an_error():
    """The header-fuzz pin: a hostile/oversized/malformed traceparent
    must never change the HTTP outcome — the request serves 200 and
    the response bytes are identical to the headerless request."""
    server = RelayServer(RelayStore()).start()
    try:
        body = protocol.encode_sync_request(_sync_request("fuzz", _msgs(1, 1)))
        baseline = _http_post(server.url + "/", body)
        for hdr in (
            "garbage", "00", "00-zz-xx-01", "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",
            "00-" + "0" * 32 + "-" + "0" * 16 + "-00",
            "x" * 8192, "00-" + "a" * 4096 + "-b-01", "\x7f\x01\x02",
            "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01-" + "y" * 4000,
        ):
            out = _http_post(server.url + "/", body,
                             headers={trace.TRACEPARENT_HEADER: hdr})
            assert out == baseline, f"header {hdr[:40]!r} changed the response"
    finally:
        server.stop()


# --- client transport hop ---


def test_sync_transport_sends_traceparent_of_the_mutation_trace():
    from evolu_tpu.core.types import Owner
    from evolu_tpu.runtime.messages import SyncRequestInput
    from evolu_tpu.sync.client import SyncTransport
    from evolu_tpu.utils.config import Config

    seen = {}

    def capturing_post(url, body, headers=None):
        seen["headers"] = headers or {}
        # An empty, valid sync response.
        return protocol.encode_sync_response(protocol.SyncResponse((), "{}"))

    transport = SyncTransport(
        Config(sync_url="http://example.invalid"),
        on_receive=lambda *a: None, http_post=capturing_post,
    )
    try:
        root = trace.start_span("client.mutate")
        transport.request_sync(SyncRequestInput(
            messages=(), clock_timestamp=timestamp_to_string(
                Timestamp(BASE, 0, "00000000000000aa")),
            merkle_tree="{}", owner=Owner("o", "m"), trace=root.context,
        ))
        transport.flush()
        root.end()
        hdr = seen["headers"].get(trace.TRACEPARENT_HEADER)
        assert hdr is not None and root.trace_id in hdr
        # The round span joined the mutation's trace in the ring.
        names = [s.name for s in trace.spans_for(root.trace_id)]
        assert "sync.round" in names
    finally:
        transport.stop()


def test_worker_send_mints_the_mutation_root_span():
    from evolu_tpu.runtime.client import create_evolu

    evolu = create_evolu({"todo": ("title",)})
    pushed = []
    evolu.worker.post_sync = pushed.append
    try:
        evolu.create("todo", {"title": "traced"})
        evolu.worker.flush()
        (req,) = pushed[-1:]
        assert req.trace is not None
        spans = trace.spans_for(req.trace.trace_id)
        assert [s.name for s in spans] == ["client.mutate"]
        assert spans[0].attrs["messages"] >= 1
    finally:
        evolu.dispose()


# --- the acceptance scenario ---


def _fleet_pair(forward: bool, scheduler=None):
    """A 2-relay fleet with hour-long gossip intervals (everything
    must ride the hint chain) and replication UNSCOPED from placement:
    the episode wants a full replication edge so one trace can cross
    routing AND gossip — a production R=2 fleet gets the same edge
    from its replica set; with only two relays R=2 would also make
    every owner local and kill the routing leg under test."""
    a = RelayServer(RelayStore(), peers=[], replication_interval_s=3600)
    b = RelayServer(RelayStore(), peers=[], replication_interval_s=3600,
                    scheduler=scheduler)
    cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                      version=1, forward=forward)
    a.enable_fleet(cfg)
    b.enable_fleet(cfg)
    a.replication.fleet = None
    b.replication.fleet = None
    a.start()
    b.start()
    return a, b


def test_single_trace_id_covers_every_hop_across_the_2relay_fleet():
    """ISSUE 10 acceptance: one client mutation drives
    forward-routing → scheduler-coalesce → engine → gossip; a single
    trace id yields a span tree covering every hop via GET /trace/<id>
    on BOTH relays (queue-wait/engine split present; the batch span
    links >= 2 request spans from different owners), while wire bytes
    (v1 and v2 records) and SQLite end state stay byte-identical with
    tracing on; the convergence-lag histogram and per-(owner, peer)
    freshness gauge fire on the pulling replica."""
    sched = None
    a = b = None
    try:
        store_b = RelayStore()
        sched = SyncScheduler(store_b, max_batch=8, max_wait_s=0.4)
        a = RelayServer(RelayStore(), peers=[], replication_interval_s=3600)
        b = RelayServer(store_b, peers=[], replication_interval_s=3600,
                        scheduler=sched)
        cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                          version=1, forward=True)
        a.enable_fleet(cfg)
        b.enable_fleet(cfg)
        a.replication.fleet = None  # see _fleet_pair's rationale
        b.replication.fleet = None
        a.start()
        b.start()

        owner_fwd = _owner_for(a.fleet.ring, b.url, prefix="fw")
        owner_direct = _owner_for(b.fleet.ring, b.url, prefix="dx")
        # One v1 (OpenPGP-shaped) and one v2 (aead GCM magic) record:
        # both are opaque ciphertext to every hop — byte-identity must
        # hold for the negotiated wire exactly like the v1 wire.
        msgs_fwd = _msgs(0, 1) + _msgs(
            0, 1, t0=1, content=aead.MAGIC + b"\x00" * 44)
        msgs_direct = _msgs(7, 2)
        req_fwd = _sync_request(owner_fwd, msgs_fwd)
        req_direct = _sync_request(owner_direct, msgs_direct)

        root = trace.start_span("client.mutate")
        hdr = {trace.TRACEPARENT_HEADER: trace.format_traceparent(root.context)}
        results = {}

        def post_forwarded():
            # Client → A; A is not placed for owner_fwd → proxies the
            # UNTOUCHED body to B through /fleet/forward.
            results["fwd"] = _http_post(
                a.url + "/", protocol.encode_sync_request(req_fwd), headers=hdr)

        def post_direct():
            results["direct"] = _http_post(
                b.url + "/", protocol.encode_sync_request(req_direct))

        threads = [threading.Thread(target=post_forwarded),
                   threading.Thread(target=post_direct)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        root.end()
        assert set(results) == {"fwd", "direct"}
        # Both owners landed on B only, coalesced through ONE fused
        # engine pass (the 0.4s window spans both posts).
        assert sorted(b.store.user_ids()) == sorted([owner_fwd, owner_direct])
        assert a.store.user_ids() == []
        assert metrics.get_counter("evolu_sched_batches_total") == 1

        # Byte-identity with tracing ON: the traced, forwarded,
        # coalesced response equals the untraced per-request oracle on
        # an identical store — for the v2-bearing request too.
        trace.set_enabled(False)
        oracle = RelayStore()
        expect_fwd = serve_single_request(oracle, req_fwd)
        expect_direct = serve_single_request(oracle, req_direct)
        trace.set_enabled(True)
        assert results["fwd"] == expect_fwd
        assert results["direct"] == expect_direct
        # SQLite end state byte-identical to the untraced oracle.
        for uid in (owner_fwd, owner_direct):
            assert b.store.get_merkle_tree_string(uid) == \
                oracle.get_merkle_tree_string(uid)
            assert b.store.replica_messages(uid, "") == \
                oracle.replica_messages(uid, "")
        oracle.close()

        # Gossip: B's manager holds BOTH writes' origin traces (the
        # round's span parents the FIRST and links the rest — either
        # order is correct behavior; pin the forwarded mutation first
        # so the assertions below are deterministic). Peer B with A —
        # B's summary POST carries the origin context, A's
        # serve_summary arms A's hint with it, A's round pulls and
        # ingests INTO THE SAME TRACE.
        with b.replication._cv:
            b.replication._hint_origins.sort(
                key=lambda o: o.trace_id != root.trace_id)
        b.replication.add_peer(a.url)
        deadline = time.time() + 10
        while time.time() < deadline and not a.replication._hint_origins:
            time.sleep(0.02)
        assert a.replication._hint_origins, "origin context never reached A"
        assert a.replication._hint_origins[0].trace_id == root.trace_id
        a.replication.add_peer(b.url)
        deadline = time.time() + 20
        while time.time() < deadline:
            if sorted(a.store.user_ids()) == sorted([owner_fwd, owner_direct]):
                break
            time.sleep(0.05)
        assert sorted(a.store.user_ids()) == sorted([owner_fwd, owner_direct])
        for uid in (owner_fwd, owner_direct):
            assert a.store.get_merkle_tree_string(uid) == \
                b.store.get_merkle_tree_string(uid)

        # ONE trace id covers every hop, served by BOTH relays (the
        # /trace surface is per-process; in-process test relays share
        # the ring — each must answer the full tree).
        for url in (a.url, b.url):
            got = json.loads(_get(url + f"/trace/{root.trace_id}"))
            names = {s["name"] for s in got["spans"]}
            assert {
                "client.mutate",       # client
                "relay.sync",          # arrival at A
                "fleet.forward",       # A → B proxy leg
                "fleet.forward.serve",  # serve at B
                "sched.queue",         # queue-wait split
                "engine.batch",        # fused engine pass (linked)
                "relay.respond",       # respond split
                "repl.round",          # gossip round (origin trace)
                "repl.summary",        # gossip HTTP legs
                "repl.pull",
                "repl.serve",          # serving side of gossip
                "repl.ingest",         # visible at replica A
            } <= names, f"missing hops: {sorted(names)}"
        # The fan-in contract: the ONE batch span links BOTH request
        # spans, which belong to different traces and owners.
        (batch,) = [s for s in trace.recorder.dump() if s.name == "engine.batch"]
        assert batch.attrs["requests"] == 2 and batch.attrs["owners"] == 2
        assert len(batch.links) == 2
        assert len({t for t, _ in batch.links}) == 2  # two distinct traces
        assert any(t == root.trace_id for t, _ in batch.links)
        # Queue-wait/engine split: both spans exist in the trace with
        # real durations.
        spans = trace.spans_for(root.trace_id)
        (q,) = [s for s in spans if s.name == "sched.queue"]
        assert q.duration_ms >= 0
        assert any(s.name == "engine.batch" for s in spans)
        # Parentage, not just presence: the serve at B nests under the
        # forward hop at A, which nests under A's server span.
        (fwd,) = [s for s in spans if s.name == "fleet.forward"]
        (fws,) = [s for s in spans if s.name == "fleet.forward.serve"]
        (a_sync,) = [s for s in spans if s.name == "relay.sync"]
        assert fws.parent_id == fwd.span_id
        assert fwd.parent_id == a_sync.span_id

        # Convergence plane on the pulling replica (A): the freshness
        # watermark equals the newest HLC millis it ingested per
        # owner, and the write→visible histogram carries the origin
        # trace as its exemplar.
        for uid, msgs in ((owner_fwd, msgs_fwd), (owner_direct, msgs_direct)):
            newest = BASE + (len(msgs) - 1) * 1000
            assert metrics.registry.get_gauge(
                "evolu_conv_owner_freshness_millis",
                replica=a.replication.replica_id, peer=b.url.rstrip("/"),
                owner=uid,
            ) == newest
        hist = metrics.registry.get_histogram(
            "evolu_conv_write_visible_ms",
            replica=a.replication.replica_id, peer=b.url.rstrip("/"),
        )
        assert hist is not None and hist[3] >= 2
        exemplar = metrics.registry.get_exemplar(
            "evolu_conv_write_visible_ms",
            replica=a.replication.replica_id, peer=b.url.rstrip("/"),
        )
        assert exemplar is not None and exemplar[0] == root.trace_id
    finally:
        for s in (a, b):
            if s is not None:
                s.stop()


def test_redirect_leg_joins_the_same_trace():
    """Redirect-mode fleet: the 307 bounce at the non-placed relay and
    the serve at the authoritative relay both land in the mutation's
    trace (the client re-sends the same traceparent after following,
    exactly like sync/client.py does)."""
    a = b = None
    try:
        a, b = _fleet_pair(forward=False)
        owner_b = _owner_for(a.fleet.ring, b.url, prefix="rd")
        body = protocol.encode_sync_request(_sync_request(owner_b, _msgs(3, 2)))
        root = trace.start_span("client.mutate")
        hdr = {trace.TRACEPARENT_HEADER: trace.format_traceparent(root.context)}
        with pytest.raises(urllib.error.HTTPError) as e:
            _http_post(a.url + "/", body, headers=hdr)
        assert e.value.code == 307
        target = e.value.headers["Location"]
        _http_post(target, body, headers=hdr)
        root.end()
        spans = trace.spans_for(root.trace_id)
        names = [s.name for s in spans]
        assert "fleet.redirect" in names  # the bounce, at A
        # Two relay.sync spans in one trace: the 307'd arrival and the
        # authoritative serve.
        assert names.count("relay.sync") == 2
    finally:
        for s in (a, b):
            if s is not None:
                s.stop()


def test_tracing_disabled_serves_identically_with_empty_ring():
    server = RelayServer(RelayStore()).start()
    try:
        trace.set_enabled(False)
        body = protocol.encode_sync_request(_sync_request("quiet", _msgs(2, 2)))
        _http_post(server.url + "/", body, headers={
            trace.TRACEPARENT_HEADER: "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01",
        })
        assert trace.recorder.dump() == []
        assert json.loads(_get(server.url + "/trace"))["recent"] == []
    finally:
        trace.set_enabled(True)
        server.stop()
