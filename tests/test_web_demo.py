"""The visual demo (examples/web_todo.py) — API-level drive of the
reference TodoMVC capabilities (examples/nextjs/pages/index.tsx): CRUD
with soft-delete and categories, long-poll reactivity, owner lifecycle,
and two demo instances converging through a live relay."""

import json
import sys
import threading
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))

from web_todo import DemoApp, DemoServer  # noqa: E402

from evolu_tpu.server.relay import RelayServer  # noqa: E402


def _api(base, path, body=None):
    if body is None:
        r = urllib.request.urlopen(base + path, timeout=30)
    else:
        req = urllib.request.Request(
            base + path, data=json.dumps(body).encode(),
            headers={"content-type": "application/json"},
        )
        r = urllib.request.urlopen(req, timeout=30)
    return json.loads(r.read())


def test_web_demo_crud_longpoll_and_reset():
    server = DemoServer(DemoApp()).start()
    base = server.url
    try:
        page = urllib.request.urlopen(base + "/", timeout=10).read().decode()
        assert "TodoMVC" in page
        cat = _api(base, "/api/mutate", {"table": "todoCategory", "values": {"name": "home"}})["id"]
        t1 = _api(base, "/api/mutate", {"table": "todo",
                  "values": {"title": "Buy milk", "isCompleted": False, "categoryId": cat}})["id"]
        s1 = _api(base, "/api/state?since=-1")
        assert [t["title"] for t in s1["todos"]] == ["Buy milk"]
        assert s1["todos"][0]["categoryId"] == cat
        assert s1["owner"]["mnemonic"]

        # Long-poll wakes on mutation (the reactive-store contract).
        got = {}
        th = threading.Thread(
            target=lambda: got.update(_api(base, f"/api/state?since={s1['version']}"))
        )
        th.start()
        time.sleep(0.3)
        _api(base, "/api/mutate", {"table": "todo", "values": {"id": t1, "isCompleted": True}})
        th.join(timeout=10)
        assert not th.is_alive() and got["version"] > s1["version"]
        assert got["todos"][0]["isCompleted"] == 1

        _api(base, "/api/mutate", {"table": "todo", "values": {"id": t1, "isDeleted": True}})
        assert _api(base, "/api/state?since=-1")["todos"] == []

        _api(base, "/api/reset", {})
        s = _api(base, "/api/state?since=-1")
        assert s["todos"] == [] and s["categories"] == []
    finally:
        server.stop()


def test_two_demos_converge_through_relay():
    relay = RelayServer().start()
    a = DemoServer(DemoApp(sync_url=relay.url)).start()
    mnemonic = a.app.evolu.owner.mnemonic
    b = DemoServer(DemoApp(sync_url=relay.url, mnemonic=mnemonic)).start()
    try:
        _api(a.url, "/api/mutate", {"table": "todo",
             "values": {"title": "from A", "isCompleted": False}})
        # B never syncs explicitly: the demo's periodic auto-pull (the
        # reference's load/online/focus trigger analog) must converge
        # an IDLE instance on its own.
        deadline = time.time() + 25
        titles = []
        while time.time() < deadline:
            titles = [t["title"] for t in _api(b.url, "/api/state?since=-1")["todos"]]
            if titles:
                break
            time.sleep(0.4)
        assert titles == ["from A"]
    finally:
        try:
            a.stop()
        finally:
            try:
                b.stop()
            finally:
                relay.stop()
