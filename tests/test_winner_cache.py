"""HBM-resident winner cache (ops/winner_cache.py) — state parity with
the streamed-winner production path across multi-batch steady state,
lazy seeding from a pre-populated store, non-canonical fallback with
invalidation, and the transaction-failure resync hook."""

import numpy as np
import pytest

from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.ops.winner_cache import DeviceWinnerCache
from evolu_tpu.storage.apply import apply_messages
from evolu_tpu.storage.native import open_database
from evolu_tpu.storage.schema import init_db_model

BASE = 1_700_000_000_000


def _db():
    db = open_database(":memory:", "auto")
    init_db_model(db, mnemonic=None)
    db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title" BLOB, "done" BLOB)')
    return db


def _mk(i, node="a1b2c3d4e5f60718", row=None, col="title", value=None):
    return CrdtMessage(
        timestamp_to_string(Timestamp(BASE + i * 977, i % 4, node)),
        "todo", row or f"r{i % 23}", col, value if value is not None else f"v{i}",
    )


def _dump(db):
    return (
        db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'),
        db.exec('SELECT * FROM "todo" ORDER BY "id"'),
    )


def test_cache_matches_streamed_path_across_batches():
    """Three successive batches with overlapping cells: the cached
    planner's SQLite end state and tree must equal the streamed-winner
    device planner's, batch by batch."""
    from evolu_tpu.ops.merge import plan_batch_device_full

    rng = np.random.default_rng(11)
    db_a, db_b = _db(), _db()
    cache = DeviceWinnerCache(db_b, capacity=64)  # force growth too
    tree_a, tree_b = {}, {}
    try:
        for batch_no in range(3):
            order = rng.permutation(120)
            batch = tuple(_mk(int(i) + batch_no * 40) for i in order)
            tree_a = apply_messages(db_a, tree_a, batch, planner=plan_batch_device_full)
            tree_b = apply_messages(db_b, tree_b, batch, planner=cache.plan_batch)
            assert _dump(db_a) == _dump(db_b), f"batch {batch_no}"
            assert merkle_tree_to_string(tree_a) == merkle_tree_to_string(tree_b)
    finally:
        db_a.close(), db_b.close()


def test_cache_seeds_from_prepopulated_store():
    """A cache created over a store that already has history must seed
    winners lazily from SQLite — a newer-than-stored message upserts, an
    older one does not."""
    db = _db()
    try:
        tree = apply_messages(db, {}, (_mk(50, row="rX"),))
        cache = DeviceWinnerCache(db, adaptive=False)  # pins lazy-seed behavior
        older = CrdtMessage(
            timestamp_to_string(Timestamp(BASE + 1, 0, "b" * 16)), "todo", "rX", "title", "OLD"
        )
        newer = CrdtMessage(
            timestamp_to_string(Timestamp(BASE + 10**9, 0, "b" * 16)), "todo", "rX", "title", "NEW"
        )
        tree = apply_messages(db, tree, (older,), planner=cache.plan_batch)
        assert db.exec_sql_query('SELECT "title" FROM "todo" WHERE "id" = ?', ("rX",)) == [{"title": "v50"}]
        tree = apply_messages(db, tree, (newer,), planner=cache.plan_batch)
        assert db.exec_sql_query('SELECT "title" FROM "todo" WHERE "id" = ?', ("rX",)) == [{"title": "NEW"}]
    finally:
        db.close()


def test_non_canonical_batch_falls_back_and_invalidates():
    """Uppercase node hex routes to the host oracle (raw-string order,
    verbatim hashing) and drops touched cells so the numeric cache never
    serves a non-canonical winner. End state equals the default path."""
    from evolu_tpu.storage.apply import plan_batch, fetch_existing_winners

    db_a, db_b = _db(), _db()
    cache = DeviceWinnerCache(db_b)
    weird = (
        CrdtMessage("2023-09-01T10:00:00.000Z-0000-ABCDEF0123456789", "todo", "rw", "title", "U"),
        CrdtMessage("2023-09-01T10:00:00.000Z-0000-abcdef0123456789", "todo", "rw", "title", "L"),
    )
    clean_then = (_mk(900, row="rw"),)
    try:
        tree_a = apply_messages(db_a, {}, weird)
        tree_b = apply_messages(db_b, {}, weird, planner=cache.plan_batch)
        assert ("todo", "rw", "title") not in cache._slots  # invalidated
        tree_a = apply_messages(db_a, tree_a, clean_then)
        tree_b = apply_messages(db_b, tree_b, clean_then, planner=cache.plan_batch)
        assert _dump(db_a) == _dump(db_b)
        assert merkle_tree_to_string(tree_a) == merkle_tree_to_string(tree_b)
    finally:
        db_a.close(), db_b.close()


def test_production_routing_through_worker():
    """backend="tpu" + winner_cache (the default) routes client
    receives through the HBM cache: the planner advertises
    fetches_winners=False, the cache fills, end state matches a
    cpu-backend client, and reset_owner drops the cache."""
    from evolu_tpu.core.merkle import merkle_tree_to_string as tree_str
    from evolu_tpu.runtime.client import create_evolu
    from evolu_tpu.storage.clock import read_clock
    from evolu_tpu.utils.config import Config

    schema = {"todo": ("title", "isCompleted")}
    hot = create_evolu(schema, config=Config(backend="tpu", winner_cache=True))
    cpu = create_evolu(schema, config=Config(backend="cpu"), mnemonic=hot.owner.mnemonic)
    try:
        cache = hot.worker._planner.cache
        assert cache is not None and not hot.worker._planner.fetches_winners
        cache.adaptive = False  # pin cached mode: this test asserts slot state
        messages = tuple(_mk(i, node=f"{(i % 5) + 1:016x}") for i in range(300))
        for c in (hot, cpu):
            c.receive(messages, "{}", None)
            c.worker.flush()
        assert cache._slots, "cache never engaged"
        assert (
            hot.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
            == cpu.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
        )
        assert tree_str(read_clock(hot.db).merkle_tree) == tree_str(
            read_clock(cpu.db).merkle_tree
        )
        hot.reset_owner()
        hot.worker.flush()
        assert not cache._slots  # dropped with the tables
    finally:
        hot.dispose(), cpu.dispose()


def test_slot_reuse_never_leaks_stale_keys():
    """An invalidated cell's slot goes to the free list; when a NEW
    cell (with no SQLite history) reuses it, the slot must read as
    no-winner — not the previous cell's keys, which would wrongly
    suppress the new cell's first upsert."""
    db = _db()
    cache = DeviceWinnerCache(db, adaptive=False)  # slot-state test
    try:
        # Occupy a slot with a large winner for cell rA.
        tree = apply_messages(db, {}, (_mk(10**6, row="rA"),), planner=cache.plan_batch)
        slot_a = cache._slots[("todo", "rA", "title")]
        cache.invalidate([("todo", "rA", "title")])
        assert slot_a in cache._free
        # A brand-new cell reuses the slot; its (small) first message
        # must still upsert.
        small = CrdtMessage(
            timestamp_to_string(Timestamp(BASE, 0, "c" * 16)), "todo", "rNEW", "title", "first"
        )
        tree = apply_messages(db, tree, (small,), planner=cache.plan_batch)
        assert cache._slots[("todo", "rNEW", "title")] == slot_a  # reused
        assert db.exec_sql_query(
            'SELECT "title" FROM "todo" WHERE "id" = ?', ("rNEW",)
        ) == [{"title": "first"}]
        # And the free list does not grow without bound across cycles.
        assert len(cache._free) == 0
    finally:
        db.close()


def test_chunked_on_chunk_failure_fires_cache_resync(tmp_path):
    """apply_messages_chunked: an `on_chunk` failure rolls the chunk
    back AFTER apply_messages returned — the winner cache (already
    scatter-advanced) must still resync, or redelivery sees phantom
    winners (xor=False forever: permanent digest divergence)."""
    from evolu_tpu.core.merkle import merkle_tree_to_string
    from evolu_tpu.storage.apply import ChunkedApplyError, apply_messages_chunked

    db = _db()
    cache = DeviceWinnerCache(db, adaptive=False)  # scatter-ahead state must exist
    msgs = tuple(_mk(i, row=f"c{i}") for i in range(6))
    try:
        with pytest.raises(ChunkedApplyError):
            apply_messages_chunked(
                db, {}, msgs, chunk_size=3, planner=cache.plan_batch,
                on_chunk=lambda tree, n: (_ for _ in ()).throw(RuntimeError("persist failed")),
            )
        assert not cache._slots, "cache kept phantom winners after rollback"
        # Redelivery must fully apply: rows upserted, hashes in tree.
        tree = apply_messages(db, {}, msgs, planner=cache.plan_batch)
        rows = db.exec_sql_query('SELECT COUNT(*) AS n FROM "todo"')
        assert rows == [{"n": 6}]
        db_cmp = _db()
        expect = apply_messages(db_cmp, {}, msgs)
        assert merkle_tree_to_string(tree) == merkle_tree_to_string(expect)
        db_cmp.close()
    finally:
        db.close()


def test_chunked_receive_through_worker_with_cache():
    """Chunked receive with the cache engaged on EVERY chunk
    (backend="tpu" → threshold 0): chunk N+1's stored winners come from
    the HBM scatter of chunk N, not a SQLite re-read — end state must
    equal a cpu-backend whole-batch client, including cross-chunk cell
    overlap where a later chunk carries an OLDER timestamp for a cell
    an earlier chunk already won."""
    from evolu_tpu.core.merkle import merkle_tree_to_string
    from evolu_tpu.runtime.client import create_evolu
    from evolu_tpu.storage.clock import read_clock
    from evolu_tpu.utils.config import Config

    schema = {"todo": ("title", "isCompleted")}
    chunked = create_evolu(
        schema, config=Config(backend="tpu", receive_chunk_size=50)
    )
    whole = create_evolu(
        schema, config=Config(backend="cpu", receive_chunk_size=None),
        mnemonic=chunked.owner.mnemonic,
    )
    # 180 messages over 30 cells: chunks overlap cells, and message
    # order is descending within some cells so later chunks lose.
    messages = tuple(
        _mk((37 * i) % 180, node=f"{(i % 7) + 1:016x}", row=f"r{i % 30}")
        for i in range(180)
    )
    try:
        cache = chunked.worker._planner.cache
        assert cache is not None
        cache.adaptive = False  # pin the HBM scatter chain this test exercises
        for c in (chunked, whole):
            c.receive(messages, "{}", None)
            c.worker.flush()
        assert cache._slots  # engaged across chunks
        assert (
            chunked.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
            == whole.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
        )
        assert (
            chunked.db.exec('SELECT * FROM "todo" ORDER BY "id"')
            == whole.db.exec('SELECT * FROM "todo" ORDER BY "id"')
        )
        assert merkle_tree_to_string(read_clock(chunked.db).merkle_tree) == \
            merkle_tree_to_string(read_clock(whole.db).merkle_tree)
    finally:
        chunked.dispose(), whole.dispose()


def test_command_level_rollback_resyncs_cache():
    """The livelock SyncError is raised AFTER apply_messages returns,
    inside the worker's one-transaction-per-command scope: the command
    rolls back but the cache already scattered forward. Without the
    command-boundary resync hook, redelivery sees phantom winners —
    xor=False forever (hashes never enter the tree) and beats=False
    (app rows never upserted). Found by tests/test_model_check.py."""
    from evolu_tpu.core.merkle import (
        create_initial_merkle_tree,
        diff_merkle_trees,
        insert_into_merkle_tree,
        merkle_tree_to_string,
    )
    from evolu_tpu.core.types import SyncError
    from evolu_tpu.runtime.client import create_evolu
    from evolu_tpu.storage.clock import read_clock
    from evolu_tpu.utils.config import Config

    schema = {"todo": ("title",)}
    hot = create_evolu(schema, config=Config(backend="tpu"))
    # Pin the static cached path: the adaptive gate would stream a
    # fresh cache's first batches and the scatter-ahead state this
    # regression test exists to exercise would never form.
    hot.worker._planner.cache.adaptive = False
    cpu = create_evolu(schema, config=Config(backend="cpu"), mnemonic=hot.owner.mnemonic)
    msgs = tuple(_mk(i, node="9" * 16, row=f"rl{i}") for i in range(8))
    try:
        # Server tree = post-apply local tree + one phantom hash the
        # client never receives: diff(server, local_after) == phantom's
        # minute. Passing that minute as previous_diff makes _receive
        # apply the batch and THEN raise the livelock SyncError.
        expect_local = create_initial_merkle_tree()
        for m in msgs:
            from evolu_tpu.core.timestamp import timestamp_from_string

            expect_local = insert_into_merkle_tree(
                timestamp_from_string(m.timestamp), expect_local
            )
        phantom = Timestamp(BASE + 10**9, 0, "8" * 16)
        server_tree = insert_into_merkle_tree(phantom, expect_local)
        prev = diff_merkle_trees(server_tree, expect_local)
        assert prev is not None

        errors = []
        hot.subscribe_error(lambda e: errors.append(e))
        for client in (hot, cpu):
            client.receive(msgs, merkle_tree_to_string(server_tree), prev)
            client.worker.flush()
        assert errors and isinstance(errors[-1], SyncError)
        assert hot.db.exec('SELECT COUNT(*) FROM "__message"') == [(0,)]  # rolled back

        # Redelivery must fully apply on BOTH backends identically.
        for client in (hot, cpu):
            client.receive(msgs, "{}", None)
            client.worker.flush()
        assert (
            hot.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
            == cpu.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
        )
        assert hot.db.exec('SELECT COUNT(*) FROM "todo"') == [(8,)]
        th = merkle_tree_to_string(read_clock(hot.db).merkle_tree)
        tc = merkle_tree_to_string(read_clock(cpu.db).merkle_tree)
        assert th == tc == merkle_tree_to_string(expect_local)
    finally:
        hot.dispose(), cpu.dispose()


def test_transaction_failure_resets_cache():
    """If the transaction rolls back after planning, the cache (already
    scattered forward) must resync — the same message applied again
    must still XOR/upsert correctly."""
    db = _db()
    cache = DeviceWinnerCache(db, adaptive=False)  # scatter-ahead state must exist
    msg = _mk(7, row="rF")
    try:
        real_apply = db.apply_planned
        calls = {"n": 0}

        def exploding(messages, mask):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("disk full")
            return real_apply(messages, mask)

        db.apply_planned = exploding
        with pytest.raises(RuntimeError):
            apply_messages(db, {}, (msg,), planner=cache.plan_batch)
        assert not cache._slots  # reset
        tree = apply_messages(db, {}, (msg,), planner=cache.plan_batch)
        assert db.exec_sql_query('SELECT "title" FROM "todo" WHERE "id" = ?', ("rF",)) == [{"title": "v7"}]
        rows = db.exec_sql_query('SELECT COUNT(*) AS n FROM "__message"')
        assert rows == [{"n": 1}]
        assert tree  # hash entered the tree exactly once
    finally:
        db.apply_planned = real_apply
        db.close()


def test_foreign_write_resets_cache(tmp_path):
    """A SECOND connection writing the same database file moves SQLite's
    data_version; the next plan_batch must drop the cache and re-seed
    from SQLite instead of serving a stale winner (advisor r2: a foreign
    apply could otherwise upsert losers over newer committed winners)."""
    path = str(tmp_path / "shared.db")
    db = open_database(path, "auto")
    init_db_model(db, mnemonic=None)
    db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title" BLOB, "done" BLOB)')
    cache = DeviceWinnerCache(db, adaptive=False)  # slot-state test
    try:
        tree = apply_messages(db, {}, (_mk(5, row="rF"),), planner=cache.plan_batch)
        assert ("todo", "rF", "title") in cache._slots

        # A foreign connection commits a NEWER winner for the same cell,
        # bypassing this worker (and so the cache) entirely.
        foreign = open_database(path, "auto")
        newer = CrdtMessage(
            timestamp_to_string(Timestamp(BASE + 10**9, 0, "f" * 16)),
            "todo", "rF", "title", "FOREIGN",
        )
        apply_messages(foreign, {}, (newer,))
        foreign.close()

        # An older-than-foreign (but newer-than-local) message must LOSE:
        # with a stale cache it would have won and clobbered "FOREIGN".
        loser = CrdtMessage(
            timestamp_to_string(Timestamp(BASE + 10**6, 0, "c" * 16)),
            "todo", "rF", "title", "LOSER",
        )
        apply_messages(db, tree, (loser,), planner=cache.plan_batch)
        assert db.exec_sql_query(
            'SELECT "title" FROM "todo" WHERE "id" = ?', ("rF",)
        ) == [{"title": "FOREIGN"}]
    finally:
        db.close()


def test_adaptive_gating_crosses_modes_with_identical_state():
    """Hysteresis (VERDICT r2 #3): a churn burst (every batch all-new
    cells) flips the planner to streaming; a steady phase decays the
    EWMA and warms the cache back up; a second burst flips it again.
    End state must equal the static streamed planner throughout."""
    from evolu_tpu.ops.merge import plan_batch_device_full

    rng = np.random.default_rng(21)
    db_a, db_b = _db(), _db()
    cache = DeviceWinnerCache(db_b, capacity=64)
    tree_a, tree_b = {}, {}
    modes = []
    try:
        def batches():
            # burst: 3 batches of brand-new cells each
            for b in range(3):
                yield [_mk(b * 200 + j, row=f"burst{b}_{j % 40}") for j in range(120)]
            # steady: 5 batches over one fixed population
            for b in range(5):
                order = rng.permutation(120)
                yield [_mk(1000 + b * 40 + int(i), row=f"s{int(i) % 23}") for i in order]
            # second burst
            for b in range(3):
                yield [_mk(3000 + b * 200 + j, row=f"b2_{b}_{j % 40}") for j in range(120)]

        for batch in batches():
            batch = tuple(batch)
            tree_a = apply_messages(db_a, tree_a, batch, planner=plan_batch_device_full)
            tree_b = apply_messages(db_b, tree_b, batch, planner=cache.plan_batch)
            modes.append(cache._streaming)
            assert _dump(db_a) == _dump(db_b)
            assert merkle_tree_to_string(tree_a) == merkle_tree_to_string(tree_b)
        # Burst 1 must have triggered streaming; the steady phase must
        # have returned to cached; burst 2 must stream again.
        assert any(modes[:3]), modes
        assert not modes[7], modes  # cached again by the end of steady
        assert any(modes[8:]), modes
    finally:
        db_a.close(), db_b.close()


def test_reset_reseed_batch_does_not_count_as_churn():
    """The first batch after reset() re-seeds every cell it touches;
    that 1.0 new-cell rate is recovery, not churn, and must not flip a
    steady workload into streamed mode (advisor r3: each unrelated
    rollback cost ~3 streamed batches before this fix)."""
    from evolu_tpu.ops.merge import plan_batch_device_full

    rng = np.random.default_rng(33)
    db_a, db_b = _db(), _db()
    cache = DeviceWinnerCache(db_b, capacity=64)
    tree_a, tree_b = {}, {}
    try:
        def steady(base):
            order = rng.permutation(120)
            return tuple(_mk(base + int(i), row=f"s{int(i) % 23}") for i in order)

        for b in range(4):  # settle into cached mode
            batch = steady(b * 40)
            tree_a = apply_messages(db_a, tree_a, batch, planner=plan_batch_device_full)
            tree_b = apply_messages(db_b, tree_b, batch, planner=cache.plan_batch)
        assert not cache._streaming
        ewma_before = cache._seed_ewma

        cache.on_transaction_failed()  # e.g. an unrelated rollback

        for b in range(4, 6):
            batch = steady(b * 40)
            tree_a = apply_messages(db_a, tree_a, batch, planner=plan_batch_device_full)
            tree_b = apply_messages(db_b, tree_b, batch, planner=cache.plan_batch)
            assert not cache._streaming, "re-seed batch was scored as churn"
            assert _dump(db_a) == _dump(db_b)
            assert merkle_tree_to_string(tree_a) == merkle_tree_to_string(tree_b)
        assert cache._seed_ewma <= ewma_before + 1e-9
    finally:
        db_a.close(), db_b.close()


def test_per_batch_foreign_writes_still_reach_streaming(tmp_path):
    """A foreign writer touching the DB between EVERY batch resets the
    cache each time; the skip-once rule must not swallow every 1.0
    re-seed rate or the gate starves and re-seeds the whole cache from
    SQLite forever (r4 review finding) — sustained resets ARE the
    churn signal, so streaming must engage."""
    db = open_database(str(tmp_path / "wc.db"), "auto")
    init_db_model(db, mnemonic=None)
    db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title" BLOB, "done" BLOB)')
    foreign = open_database(str(tmp_path / "wc.db"), "auto")
    cache = DeviceWinnerCache(db, capacity=64)
    rng = np.random.default_rng(5)
    tree = {}
    try:
        streamed = []
        for b in range(6):
            foreign.exec("CREATE TABLE IF NOT EXISTS _poke (x)")
            foreign.exec("INSERT INTO _poke VALUES (1)")  # moves data_version
            order = rng.permutation(120)
            batch = tuple(_mk(b * 40 + int(i), row=f"s{int(i) % 23}") for i in order)
            tree = apply_messages(db, tree, batch, planner=cache.plan_batch)
            streamed.append(cache._streaming)
        assert any(streamed), (
            f"gate starved: every re-seed rate was suppressed {streamed}"
        )
    finally:
        db.close(), foreign.close()


def test_disable_adaptive_while_streaming_reseeds_safely():
    """Flipping adaptive=False on a cache that is ALREADY streaming
    must fall back to the cached path with a full reseed — not look up
    previously-streamed cells in the (empty) slot table (regression:
    KeyError aborting the apply transaction)."""
    db = _db()
    cache = DeviceWinnerCache(db)
    try:
        first = tuple(_mk(i, row=f"s{i}") for i in range(6))
        tree = apply_messages(db, {}, first, planner=cache.plan_batch)
        assert cache._streaming  # fresh cache streams its first batch
        cache.adaptive = False
        again = tuple(_mk(100 + i, row=f"s{i}") for i in range(6))
        apply_messages(db, tree, again, planner=cache.plan_batch)
        assert not cache._streaming and cache._slots
        assert db.exec_sql_query('SELECT COUNT(*) AS n FROM "__message"') == [{"n": 12}]
    finally:
        db.close()


class _PbStub:
    """The minimal PackedReceive surface `plan_packed` touches before
    the seed branch (n, parse_timestamps, touched_cells, cells,
    cell_id) — enough to drive the adaptive gate without native
    crypto."""

    def __init__(self, messages):
        from evolu_tpu.ops.host_parse import intern_cells

        self.n = len(messages)
        self._ts = [m.timestamp for m in messages]
        self.cell_id, self.cells = intern_cells(
            [m.table for m in messages], [m.row for m in messages],
            [m.column for m in messages],
        )

    def parse_timestamps(self):
        from evolu_tpu.ops.host_parse import parse_timestamp_strings

        return parse_timestamp_strings(self._ts, with_case=True)

    def touched_cells(self):
        ids = np.unique(self.cell_id)
        return ids, [self.cells[int(i)] for i in ids]


def test_plan_packed_seed_failure_samples_ewma_once(monkeypatch):
    """A non-canonical stored-winner seed bounces `plan_packed` to the
    object path, which re-enters the adaptive gate via `plan_batch` for
    the SAME batch — the bounce must arm `_skip_ewma_once` so the gate
    samples the EWMA exactly once per batch (ADVICE r5)."""
    db = _db()
    # adaptive=False pins the gate to the cached route (a fresh
    # adaptive cache's first all-new batch would stream instead of
    # seeding); the EWMA is still sampled on every gate entry, which is
    # exactly the behavior under test.
    cache = DeviceWinnerCache(db, capacity=64, adaptive=False)
    msgs = tuple(_mk(i) for i in range(40))
    try:
        monkeypatch.setattr(
            DeviceWinnerCache, "_seed_new_cells", lambda self, cells: False
        )
        assert cache.plan_packed(_PbStub(msgs)) is None
        assert cache._skip_ewma_once, "bounce did not arm the one-shot skip"
        ewma_after_packed = cache._seed_ewma
        monkeypatch.setattr(
            DeviceWinnerCache, "_host_fallback", lambda self, m, c: "HOST"
        )
        assert cache.plan_batch(msgs) == "HOST"
        assert cache._seed_ewma == ewma_after_packed, (
            "object-path re-route sampled the EWMA a second time"
        )
    finally:
        db.close()


def test_capacity_cap_drop_and_reseed_eviction():
    """Bounded cache (VERDICT #3): driving the cache past `max_slots`
    with ever-new cells must evict (drop-and-reseed), never grow past
    the cap, and keep the SQLite end state + tree byte-equal to the
    streamed-winner planner's. One batch bigger than the cap itself
    plans streamed (no cache state) — same end state."""
    from evolu_tpu.obs import metrics
    from evolu_tpu.ops.merge import plan_batch_device_full

    db_a, db_b = _db(), _db()
    cache = DeviceWinnerCache(db_b, capacity=16, adaptive=False, max_slots=40)
    tree_a, tree_b = {}, {}
    metrics.reset()
    try:
        # 6 batches × 23 distinct rows (cells rotate via the row key),
        # crossing the 40-slot cap repeatedly.
        for batch_no in range(6):
            batch = tuple(
                _mk(i + batch_no * 23, row=f"cap{batch_no}-{i}") for i in range(23)
            )
            tree_a = apply_messages(db_a, tree_a, batch, planner=plan_batch_device_full)
            tree_b = apply_messages(db_b, tree_b, batch, planner=cache.plan_batch)
            assert len(cache._slots) <= 40, f"batch {batch_no} exceeded the cap"
            assert cache._next_slot <= 64  # device slots stay bounded too
            assert _dump(db_a) == _dump(db_b), f"batch {batch_no}"
            assert merkle_tree_to_string(tree_a) == merkle_tree_to_string(tree_b)
        assert metrics.get_counter("evolu_winner_cache_evictions_total") >= 1

        # A single batch larger than the cap: streamed, still byte-equal.
        big = tuple(_mk(500 + i, row=f"big{i}") for i in range(50))
        tree_a = apply_messages(db_a, tree_a, big, planner=plan_batch_device_full)
        tree_b = apply_messages(db_b, tree_b, big, planner=cache.plan_batch)
        assert _dump(db_a) == _dump(db_b)
        assert merkle_tree_to_string(tree_a) == merkle_tree_to_string(tree_b)
        assert len(cache._slots) <= 40
    finally:
        db_a.close(), db_b.close()
