"""Batched-AEAD v2 sync wire (`aead-batch-v1`, ISSUE 8) — sync/aead.py
+ the C twin in native/evolu_crypto.cpp.

Pins the four contracts the capability rests on:
- format disjointness + parity: a v2 record can never parse as OpenPGP
  (and vice versa), and the pure/native legs produce interchangeable
  bytes — either side decrypts the other's records.
- tamper surface: mutation/truncation anywhere in a record or its
  carrying wire raises ONLY ValueError (framing) / PgpError (record),
  never wedges, never partially applies a leg.
- mixed logs: one owner negotiated and one not must land the exact
  SQLite end state of an all-v1 oracle (records self-describe; the
  store, Merkle algebra, and apply path are version-blind).
- downgrade: a failover to a relay that did not advertise the
  capability silently re-emits v1 — v2 records must never reach a
  non-negotiated relay (2-relay fleet regression).
"""

import random

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.storage import apply_messages
from evolu_tpu.sync import aead, native_crypto, protocol
from evolu_tpu.sync.client import decrypt_messages, encrypt_messages_v2
from evolu_tpu.sync.crypto import PgpError, encrypt_symmetric

from tests.test_apply import MNEMONIC as MN, dump, make_db

# Every CrdtValue kind, NULs (the char*-ABI trap), unicode, int64
# edges, float specials — the same adversarial matrix the v1 parity
# tests use.
VALUES = [
    None, "", "x", "héllo ✓ café", "with\x00nul\x00s", "日本語",
    True, False, 0, 1, -1, 2**31 - 1, -(2**31), 2**63 - 1, -(2**63),
    3.14159, -0.0, 1e308, float("inf"),
]


def _msgs(values=VALUES):
    return tuple(
        CrdtMessage(f"ts{i}", "todo\x00tbl", f"row-{i}", "col\x00umn", v)
        for i, v in enumerate(values)
    )


def _canon(m):
    v = int(m.value) if isinstance(m.value, bool) else m.value
    return CrdtMessage(m.timestamp, m.table, m.row, m.column, v)


@pytest.fixture(autouse=True)
def _fresh_sessions():
    aead.reset_sessions()
    yield
    aead.reset_sessions()


# --- record format ---


def test_record_roundtrip_and_format_disjointness():
    s = aead.get_session(MN)
    for pt in (b"", b"\x00", b"content \x00 with NULs \xff", b"x" * 5000):
        rec = aead.encrypt_record(s.key, s.salt, pt)
        assert aead.is_v2_record(rec)
        assert aead.decrypt_record(rec, MN) == pt
        assert aead.decrypt_content(rec, MN) == pt
        # A v2 record is NOT an OpenPGP packet stream: byte 0 has bit 7
        # clear, which no valid CTB can.
        with pytest.raises(PgpError):
            from evolu_tpu.sync.crypto import decrypt_symmetric

            decrypt_symmetric(rec, MN)
    # ...and an OpenPGP message is NOT a v2 record: the dispatch sends
    # it down the v1 path, where it decrypts fine.
    ct = encrypt_symmetric(b"v1 payload", MN)
    assert not aead.is_v2_record(ct)
    assert aead.decrypt_content(ct, MN) == b"v1 payload"
    # Wrong key is tamper-shaped: PgpError, not a third type.
    rec = aead.encrypt_record(s.key, s.salt, b"secret")
    with pytest.raises(PgpError):
        aead.decrypt_record(rec, "wrong mnemonic words")


def test_session_rotates_before_gcm_nonce_bound():
    """Random 96-bit nonces cap a GCM key at 2^32 invocations (NIST
    SP 800-38D); the session must retire itself WELL under that. A
    request that would cross SESSION_RECORD_LIMIT mints a fresh
    salt+key, and records sealed under the retired key stay
    decryptable (the salt rides every record)."""
    s1 = aead.get_session(MN, records=aead.SESSION_RECORD_LIMIT - 1)
    assert aead.get_session(MN) is s1  # still under the bound
    rec = aead.encrypt_record(s1.key, s1.salt, b"old key epoch")
    s2 = aead.get_session(MN, records=2)  # would cross → rotate
    assert s2 is not s1 and s2.salt != s1.salt and s2.key != s1.key
    assert s2.used == 2
    assert aead.decrypt_record(rec, MN) == b"old key epoch"


def test_session_caching_and_reset():
    s1 = aead.get_session(MN)
    assert aead.get_session(MN) is s1  # one HKDF per (owner, session)
    other = aead.get_session("other words")
    assert other.key != s1.key and other.salt != s1.salt
    aead.reset_sessions()
    s2 = aead.get_session(MN)
    assert s2 is not s1 and s2.salt != s1.salt  # fresh salt, fresh key
    # Records from the RETIRED session still decrypt (salt rides every
    # record; the decrypt side re-derives on miss).
    rec = aead.encrypt_record(s1.key, s1.salt, b"old session")
    aead.reset_sessions()
    assert aead.decrypt_record(rec, MN) == b"old session"


# --- pure <-> native parity ---


@pytest.mark.skipif(not native_crypto.native_available(),
                    reason="libevolu_crypto unavailable")
def test_native_encode_pure_decrypt_parity():
    """`ehc_aead_encrypt_wire_batch` bytes must be a decodable
    SyncRequest whose records the PURE oracle opens to the exact
    contents — the two HKDF/GCM implementations must interoperate
    bit-for-bit (same info string, same record layout)."""
    msgs = _msgs()
    s = aead.get_session(MN)
    body = native_crypto.encode_push_request_aead(
        msgs, s.key, s.salt, "user-1", "f" * 16, '{"h":1}')
    assert body is not None
    req = protocol.decode_sync_request(body)
    assert (req.user_id, req.node_id, req.merkle_tree) == ("user-1", "f" * 16, '{"h":1}')
    assert len(req.messages) == len(msgs)
    for m, e in zip(msgs, req.messages):
        assert e.timestamp == m.timestamp
        assert aead.is_v2_record(e.content)
        got = protocol.decode_content(aead.decrypt_record(e.content, MN))
        assert got == (m.table, m.row, m.column,
                       int(m.value) if isinstance(m.value, bool) else m.value)
    # Trailing scalar fields identical to the pure encoder's.
    tail = protocol.encode_sync_request(
        protocol.SyncRequest((), "user-1", "f" * 16, '{"h":1}'))
    assert body.endswith(tail)
    # Nonces are per-record random: no two records share one, and a
    # re-encode of the same batch never repeats bytes.
    nonces = {e.content[19:31] for e in req.messages}
    assert len(nonces) == len(msgs)
    body2 = native_crypto.encode_push_request_aead(
        msgs, s.key, s.salt, "user-1", "f" * 16, '{"h":1}')
    assert body2 != body


@pytest.mark.skipif(not native_crypto.native_available(),
                    reason="libevolu_crypto unavailable")
def test_pure_encode_native_decrypt_parity():
    """The reverse leg: PURE v2 records served in a response must
    decode through the fused C paths to the canonical messages (the C
    `decrypt_one` dispatches on the record magic)."""
    msgs = tuple(
        CrdtMessage(
            timestamp_to_string(
                Timestamp(1_700_000_000_000 + i * 1000, i % 4, "a1b2c3d4e5f60718")),
            "todo", f"row-{i:05d}", "title", v)
        for i, v in enumerate(VALUES)
    )
    enc = encrypt_messages_v2(msgs, MN)
    resp = protocol.encode_sync_response(protocol.SyncResponse(enc, '{"t":9}'))
    fused = native_crypto.decrypt_response(resp, MN)
    assert fused is not None
    got, tree = fused
    assert tree == '{"t":9}'
    assert got == tuple(_canon(m) for m in msgs)
    # And the object-path oracle agrees.
    assert decrypt_messages(enc, MN) == tuple(_canon(m) for m in msgs)


# --- tamper surface ---


def test_flipped_bit_pinned_cases():
    """One deliberate bit flip in EVERY region of a record — salt,
    nonce, ciphertext, tag — must surface as PgpError (the auth tag
    covers the whole record; a flipped salt derives a wrong key, which
    is indistinguishable from tamper). A flipped MAGIC demotes the
    record to the OpenPGP parser, whose malformed-packet answer is
    PgpError too — the surface never widens."""
    s = aead.get_session(MN)
    rec = aead.encrypt_record(s.key, s.salt, b"pinned payload")
    for off in (0, 1, 2, 3, 10, 19, 25, 31, len(rec) - 16, len(rec) - 1):
        bad = bytearray(rec)
        bad[off] ^= 0x40
        with pytest.raises(PgpError):
            aead.decrypt_content(bytes(bad), MN)


def test_truncated_envelope_pinned_cases():
    """Every truncation point — inside the header, inside the
    ciphertext, inside the tag — raises PgpError; prefix-extensions
    raise too (the tag authenticates exact length)."""
    s = aead.get_session(MN)
    rec = aead.encrypt_record(s.key, s.salt, b"pinned payload")
    for k in (3, 4, 18, 19, 30, 31, 46, len(rec) - 17, len(rec) - 1):
        with pytest.raises(PgpError):
            aead.decrypt_record(rec[:k], MN)
    with pytest.raises(PgpError):
        aead.decrypt_record(rec + b"\x00", MN)
    # The 46-byte boundary case: a record with EMPTY plaintext is
    # exactly RECORD_OVERHEAD long and valid…
    empty = aead.encrypt_record(s.key, s.salt, b"")
    assert len(empty) == aead.RECORD_OVERHEAD
    assert aead.decrypt_record(empty, MN) == b""
    # …one byte shorter is the canonical truncation error.
    with pytest.raises(PgpError):
        aead.decrypt_record(empty[:-1], MN)


def test_mutation_fuzz_record_native_matches_oracle():
    """120 trials of bit flips / deletions / insertions on a v2 record:
    the native batch path must produce the oracle's value or raise the
    oracle's error type — never a third outcome, never a wedge."""
    rng = random.Random(0x0E2)
    s = aead.get_session(MN)
    base = [
        aead.encrypt_record(
            s.key, s.salt, protocol.encode_content("todo", f"r{i}", "title", v))
        for i, v in enumerate(["fuzz-me", 42, None, 2.5])
    ]
    native_ok = native_crypto.native_available()
    for trial in range(120):
        ct = bytearray(rng.choice(base))
        for _ in range(rng.randint(1, 4)):
            op = rng.random()
            if op < 0.5 and ct:
                ct[rng.randrange(len(ct))] ^= 1 << rng.randrange(8)
            elif op < 0.75 and len(ct) > 2:
                del ct[rng.randrange(len(ct))]
            else:
                ct.insert(rng.randrange(len(ct) + 1), rng.randrange(256))
        enc = (protocol.EncryptedCrdtMessage("t", bytes(ct)),)
        try:
            oracle = protocol.decode_content(aead.decrypt_content(bytes(ct), MN))
        except (PgpError, ValueError) as e:
            oracle = type(e)
        assert oracle in (PgpError, ValueError) or isinstance(oracle, tuple)
        if not native_ok:
            continue
        try:
            (m,) = native_crypto.decrypt_batch(enc, MN)
            got = (m.table, m.row, m.column, m.value)
        except (PgpError, ValueError) as e:
            got = type(e)
        assert got == oracle, f"trial {trial}: oracle {oracle!r} vs got {got!r}"


def test_mutation_fuzz_response_wire_never_diverges():
    """Mutations of FULL response bytes carrying v2 records: whenever
    the fused C walker accepts the wire, its outcome equals the pure
    decode+decrypt outcome exactly (value or error type); a None means
    production runs the pure path, equal by definition."""
    if not native_crypto.native_available():
        pytest.skip("libevolu_crypto unavailable")
    rng = random.Random(0x5A17)
    enc = encrypt_messages_v2(_msgs(["a", 7, None]), MN)
    base = protocol.encode_sync_response(protocol.SyncResponse(enc, '{"x":1}'))
    for trial in range(120):
        b = bytearray(base)
        for _ in range(rng.randint(1, 5)):
            op = rng.random()
            if op < 0.6 and b:
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            elif op < 0.8 and len(b) > 2:
                del b[rng.randrange(len(b))]
            else:
                b.insert(rng.randrange(len(b) + 1), rng.randrange(256))
        data = bytes(b)
        try:
            fused = native_crypto.decrypt_response(data, MN)
        except (PgpError, ValueError) as e:
            fused = type(e)
        if fused is None:
            continue
        try:
            resp = protocol.decode_sync_response(data)
            oracle = (decrypt_messages(resp.messages, MN), resp.merkle_tree)
        except (PgpError, ValueError) as e:
            oracle = type(e)
        assert fused == oracle, f"trial {trial}"


def test_corrupt_length_prefix_fuzz_never_diverges():
    """ISSUE 16 satellite audit of the PR-13 fused/pure contract: the
    random mutation fuzz above rarely lands on the bytes that matter
    most — the varint LENGTH PREFIXES that frame every length-delimited
    field. A corrupted length re-frames everything after it (the exact
    shape of the once-divergent fixture below), so this walks the real
    encoded response, enumerates every length-prefix offset (top level
    and nested), applies deterministic worst-case corruptions to each
    (zero, max-7bit, continuation-bit flip, off-by-one both ways,
    0xFF), and asserts the fused C walker's outcome still equals the
    pure decode+decrypt oracle on every single one."""
    if not native_crypto.native_available():
        pytest.skip("libevolu_crypto unavailable")

    def length_prefix_spans(data, base=0, depth=0, out=None):
        out = [] if out is None else out
        pos = 0
        while pos < len(data):
            try:
                tag, p = protocol._read_varint(data, pos)
            except ValueError:
                break
            wt = tag & 7
            if wt == 2:
                try:
                    ln, q = protocol._read_varint(data, p)
                except ValueError:
                    break
                if ln < 0 or q + ln > len(data):
                    break
                out.append((base + p, q - p))
                if depth < 2:  # message → record → envelope fields
                    length_prefix_spans(data[q:q + ln], base + q,
                                        depth + 1, out)
                pos = q + ln
            elif wt == 0:
                try:
                    _, pos = protocol._read_varint(data, p)
                except ValueError:
                    break
            elif wt == 5:
                pos = p + 4
            elif wt == 1:
                pos = p + 8
            else:
                break
        return out

    enc = encrypt_messages_v2(_msgs(["a", 7, None]), MN)
    base = protocol.encode_sync_response(protocol.SyncResponse(enc, '{"x":1}'))
    spans = length_prefix_spans(base)
    assert len(spans) >= 4, "walker found no nested length prefixes"
    divergent = []
    for off, width in spans:
        orig = base[off]
        corruptions = {0x00, 0x7F, 0xFF, orig ^ 0x80,
                       (orig + 1) & 0xFF, (orig - 1) & 0xFF} - {orig}
        for value in sorted(corruptions):
            data = base[:off] + bytes([value]) + base[off + 1:]
            try:
                fused = native_crypto.decrypt_response(data, MN)
            except (PgpError, ValueError) as e:
                fused = type(e)
            if fused is None:  # demoted: production runs the pure path
                continue
            try:
                resp = protocol.decode_sync_response(data)
                oracle = (decrypt_messages(resp.messages, MN),
                          resp.merkle_tree)
            except (PgpError, ValueError) as e:
                oracle = type(e)
            if fused != oracle:
                divergent.append((off, width, value))
    assert divergent == [], (
        f"fused/pure outcomes diverged on corrupted length prefixes: "
        f"{divergent[:10]}"
    )


def test_tampered_leg_is_one_error_never_partial():
    """Tamper ANYWHERE in a multi-record leg surfaces as ONE PgpError
    for the whole leg — the decrypt raises before anything is
    returned, so the apply layer never sees a partial batch (exactly
    the v1 per-message MDC contract)."""
    msgs = _msgs(["a", "b", "c", 1, 2.5, None])
    enc = list(encrypt_messages_v2(msgs, MN))
    bad = bytearray(enc[3].content)
    bad[-1] ^= 0x01  # inside the GCM tag
    enc[3] = protocol.EncryptedCrdtMessage(enc[3].timestamp, bytes(bad))
    with pytest.raises(PgpError):
        decrypt_messages(tuple(enc), MN)
    if native_crypto.native_available():
        resp = protocol.encode_sync_response(
            protocol.SyncResponse(tuple(enc), "{}"))
        with pytest.raises(PgpError):
            native_crypto.decrypt_response(resp, MN)


# --- mixed v1/v2 logs ---


def test_mixed_batch_end_state_matches_all_v1_oracle():
    """One owner negotiated (v2 records), one not (v1 OpenPGP), pushed
    through the REAL relay serve path and pulled cold: the decrypted
    messages and the applied SQLite end state must be byte-identical
    to an all-v1 oracle run of the same logical messages. The store,
    Merkle algebra, and apply path never see the wire version."""
    from evolu_tpu.server.relay import RelayStore
    from tests.test_apply import random_messages

    rng = random.Random(42)
    msgs_a = tuple(random_messages(rng, 60))
    msgs_b = tuple(random_messages(rng, 60))

    def encrypted(msgs, v2):
        from evolu_tpu.sync.client import encrypt_messages

        return (encrypt_messages_v2 if v2 else encrypt_messages)(msgs, MN)

    def run(owner_wire):  # {"A": v2?, "B": v2?} → (decrypted, dumps)
        store = RelayStore()
        try:
            decrypted, dumps = {}, {}
            for owner, v2 in owner_wire.items():
                msgs = msgs_a if owner == "A" else msgs_b
                store.sync(protocol.SyncRequest(
                    encrypted(msgs, v2), owner, "b" * 16, "{}"))
            for owner in owner_wire:
                resp = store.sync(protocol.SyncRequest((), owner, "c" * 16, "{}"))
                got = decrypt_messages(resp.messages, MN)
                decrypted[owner] = got
                db = make_db()
                apply_messages(db, {}, got)
                dumps[owner] = dump(db)
            return decrypted, dumps
        finally:
            store.close()

    mixed = run({"A": True, "B": False})
    oracle = run({"A": False, "B": False})
    assert mixed[0] == oracle[0]  # same decrypted CrdtMessages…
    assert mixed[1] == oracle[1]  # …and the same SQLite end state


# --- v1 wire byte-identity when not negotiated ---


def test_v1_wire_byte_exact_when_capability_not_negotiated():
    """With `aead-batch-v1` absent from the negotiated set the
    transport's encode MUST be the pre-PR path: the fused C v1
    encoder's exact output plus the PR-7 capability suffix — and with
    nothing advertised, the v1 wire byte-for-byte (extends the PR-7
    byte-identity pin; the OpenPGP salts are the only nondeterminism,
    so the message-less framing is pinned to exact bytes and the
    message-bearing path is pinned to the exact encoder call)."""
    from evolu_tpu.core.types import Owner
    from evolu_tpu.runtime.messages import SyncRequestInput
    from evolu_tpu.sync.client import SyncTransport
    from evolu_tpu.sync.crypto import decrypt_symmetric
    from evolu_tpu.utils.config import Config

    owner = Owner(id="owner-1", mnemonic=MN)
    tr = SyncTransport(Config(sync_url="http://127.0.0.1:9"), lambda *a: None)
    try:
        node = "a1b2c3d4e5f60718"
        empty = SyncRequestInput((), "unused", '{"h":1}', owner)
        # Message-less round: fully deterministic — pin exact bytes.
        v1_bytes = protocol.encode_sync_request(
            protocol.SyncRequest((), owner.id, node, '{"h":1}'))
        assert tr._encode_push(empty, node, (), False) == v1_bytes
        caps = tuple(protocol.KNOWN_CAPABILITIES)
        assert tr._encode_push(empty, node, caps, False) == (
            v1_bytes + protocol.encode_request_capabilities(caps))
        # Message-bearing round, capability advertised but NOT
        # negotiated: every record is strict OpenPGP (decrypts via the
        # v1-only oracle; no v2 magic anywhere) and the body is the
        # pre-PR layout — v1 messages stream + scalar tail + suffix.
        push = SyncRequestInput(_msgs(["x", 1, None]), "unused", "{}", owner)
        body = tr._encode_push(push, node, caps, False)
        suffix = protocol.encode_request_capabilities(caps)
        assert body.endswith(suffix)
        req = protocol.decode_sync_request(body)
        assert req.capabilities == caps
        for e in req.messages:
            assert not aead.is_v2_record(e.content)
            decrypt_symmetric(e.content, MN)  # raises if not OpenPGP
        # The gate itself: an un-echoed relay never selects v2.
        assert not tr._aead_negotiated("http://x/", caps)
        tr.negotiated_capabilities["http://x/"] = (protocol.CAP_CRDT_TYPES,)
        assert not tr._aead_negotiated("http://x/", caps)
        tr.negotiated_capabilities["http://x/"] = (protocol.CAP_AEAD_BATCH,)
        assert tr._aead_negotiated("http://x/", caps)
        assert not tr._aead_negotiated("http://x/", ())  # not advertised
    finally:
        tr.stop()


# --- negotiation + failover downgrade ---


def test_v2_only_after_negotiation_then_fleet_failover_downgrades():
    """The emission gate end-to-end through a 2-relay fleet: the
    client sends v1 until the relay's echo lands, v2 after — and a
    FAILOVER to a replica that never advertised the capability
    silently re-emits the round as v1 (regression: the cached
    negotiated set must be invalidated alongside the cached route;
    a v2 record must never reach a non-negotiated relay)."""
    from evolu_tpu.api import model
    from evolu_tpu.obs import metrics
    from evolu_tpu.runtime.client import create_evolu
    from evolu_tpu.server.relay import RelayServer, RelayStore
    from evolu_tpu.sync.client import connect
    from evolu_tpu.utils.config import Config, FleetConfig

    SCHEMA = {"todo": ("title", "isCompleted", *model.COMMON_COLUMNS)}

    def stored_contents(server):
        return [
            bytes(r["content"]) for r in
            server.store.db.exec_sql_query('SELECT content FROM "message"')
        ]

    # A = current relay (advertises aead-batch-v1); B = a v1 replica
    # (echoes nothing). rf=2 places every owner on both, so either
    # serves locally — the failover under test is the CLIENT's.
    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = RelayServer(RelayStore(), capabilities=(), peers=[],
                    replication_interval_s=30).start()
    cfg = FleetConfig(relays=(a.url, b.url), replication_factor=2, version=1)
    a.enable_fleet(cfg)
    b.enable_fleet(cfg)
    evolu = None
    try:
        evolu = create_evolu(SCHEMA, config=Config(sync_url=b.url))
        tr = connect(evolu)
        owner = evolu.owner.id
        # The learned route points at A (as a fleet 307 would have
        # left it) — rounds go to A while it lives.
        tr._routes[owner] = a.url + "/"

        def round_trip():
            evolu.worker.flush(); tr.flush(); evolu.worker.flush()

        # Round 1: nothing negotiated yet — v1 wire, but A's echo
        # lands the capability set.
        evolu.create("todo", {"title": "r1", "isCompleted": False})
        round_trip()
        assert protocol.CAP_AEAD_BATCH in tr.negotiated_capabilities[a.url + "/"]
        assert not any(aead.is_v2_record(c) for c in stored_contents(a))
        # Round 2: negotiated — v2 records land at A.
        evolu.create("todo", {"title": "r2", "isCompleted": False})
        round_trip()
        assert any(aead.is_v2_record(c) for c in stored_contents(a))
        # A dies. The next round must fail over to the configured
        # relay B and re-emit ITSELF as v1 — B never advertised.
        a.stop()
        errors = []
        evolu.subscribe_error(errors.append)
        before = metrics.get_counter(
            "evolu_crypto_v1_fallback_total", reason="failover")
        evolu.create("todo", {"title": "r3", "isCompleted": False})
        round_trip()
        assert not errors
        assert a.url + "/" not in tr.negotiated_capabilities
        contents_b = stored_contents(b)
        assert contents_b, "failover round never reached relay B"
        assert not any(aead.is_v2_record(c) for c in contents_b), \
            "v2 record sent to a relay that did not advertise aead-batch-v1"
        assert metrics.get_counter(
            "evolu_crypto_v1_fallback_total", reason="failover") == before + 1
        # B's echo is capability-less: the gate stays v1 at B.
        assert protocol.CAP_AEAD_BATCH not in tr.negotiated_capabilities.get(
            b.url, ())
    finally:
        if evolu is not None:
            evolu.dispose()
        b.stop()
        try:
            a.stop()
        except Exception:
            pass


def test_known_divergent_malformed_wire_fixture():
    """The once-xfailed fused/pure structural divergence (PR-12 rode-along,
    fixed in PR 13): the mutated wire carries a top-level field-3
    capability whose bytes are not UTF-8 — the pure decoder's
    _decode_capability raises, but the C response walker used to SKIP
    field 3 unvalidated and decode 2 messages + an empty tree. Both
    walkers now bounce capability shapes the pure decoder rejects
    (native capability_ok), so the fused path demotes and the pure
    error surface is the only one a caller ever sees."""
    if not native_crypto.native_available():
        pytest.skip("libevolu_crypto unavailable")
    import pathlib

    data = (pathlib.Path(__file__).parent
            / "fixtures" / "fuzz_divergent_response.bin").read_bytes()
    try:
        fused = native_crypto.decrypt_response(data, MN)
    except (PgpError, ValueError) as e:
        fused = type(e)
    try:
        resp = protocol.decode_sync_response(data)
        oracle = (decrypt_messages(resp.messages, MN), resp.merkle_tree)
    except (PgpError, ValueError) as e:
        oracle = type(e)
    assert fused is None or fused == oracle
    # The fixture's specific shape: structurally valid protobuf whose
    # capability bytes fail UTF-8 — the pure decoder must raise and
    # BOTH fused walkers must demote rather than succeed.
    with pytest.raises(ValueError):
        protocol.decode_sync_response(data)
    assert native_crypto.decrypt_response(data, MN) is None
    assert native_crypto.decrypt_response_columns(data, MN) is None


def _caps_field(raw: bytes) -> bytes:
    """One top-level SyncResponse field-3 entry with raw payload bytes."""
    return bytes([0x1A, len(raw)]) + raw


def test_capability_lanes_fused_matches_pure():
    """Every capability lane the pure decoder distinguishes, pinned on
    both fused walkers: valid caps decode fused (and are surfaced by the
    separate capability scan), bad-UTF-8 caps and >64 entries demote to
    the pure decoder's ValueError."""
    if not native_crypto.native_available():
        pytest.skip("libevolu_crypto unavailable")
    from evolu_tpu.sync.client import encrypt_messages

    ts0 = timestamp_to_string(Timestamp(0, 0, "a1b2c3d4e5f60718"))
    enc = encrypt_messages(
        [CrdtMessage(ts0, "t", "r", "c", "v")], MN)
    base = protocol.encode_sync_response(
        protocol.SyncResponse(tuple(enc), "{}"))

    # Valid capability: both paths succeed with identical (messages, tree).
    ok = base + _caps_field(b"aead-batch-v1")
    fused = native_crypto.decrypt_response(ok, MN)
    resp = protocol.decode_sync_response(ok)
    oracle = (decrypt_messages(resp.messages, MN), resp.merkle_tree)
    assert fused == oracle
    assert native_crypto.decrypt_response_columns(ok, MN) is not None
    assert protocol.scan_sync_response_capabilities(ok) == ("aead-batch-v1",)

    # Bad UTF-8 capability: pure raises, fused demotes (never succeeds).
    bad = base + _caps_field(b"\xa1\xff")
    with pytest.raises(ValueError):
        protocol.decode_sync_response(bad)
    assert native_crypto.decrypt_response(bad, MN) is None
    assert native_crypto.decrypt_response_columns(bad, MN) is None

    # 65 capability entries: pure raises "too many", fused demotes.
    many = base + _caps_field(b"c") * 65
    with pytest.raises(ValueError):
        protocol.decode_sync_response(many)
    assert native_crypto.decrypt_response(many, MN) is None
    assert native_crypto.decrypt_response_columns(many, MN) is None
    # 64 entries is within the pure decoder's bound: both succeed.
    limit = base + _caps_field(b"c") * 64
    assert protocol.decode_sync_response(limit).capabilities == ("c",) * 64
    assert native_crypto.decrypt_response(limit, MN) == oracle
