"""PR-11 storage inversion: device-resident serving state + the
bounded async write-behind materializer (storage/write_behind.py).

The contract under test, end to end:
- With write-behind ON, the engine's serving path touches no btree;
  after a drain the SQLite end state is BYTE-IDENTICAL to a
  synchronous-apply oracle twin, and responses for in-sync pushes and
  cold syncs are byte-identical to the synchronous engine's.
- Duplicate delivery (client retry) converges: the optimistic serve
  tree is corrected EXACTLY at drain time; state identity holds and
  the next round's responses re-align with the oracle.
- An ACKed write is never lost: the fsync'd record log replays
  idempotently after a crash (the SIGKILL torture episode lives in
  tests/test_model_check.py; this file covers the in-process replay).
- Backpressure stalls admission (WriteBehindFull → the scheduler's
  503 + Retry-After), never drops.
- /health exposes backlog + drain watermark (saturated = not ready);
  /stats exposes the evolu_wb_* family.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server.engine import BatchReconciler
from evolu_tpu.server.relay import RelayServer, RelayStore, ShardedRelayStore
from evolu_tpu.storage.write_behind import (
    IngestRecord,
    WriteBehindFull,
    WriteBehindQueue,
)
from evolu_tpu.sync import protocol

BASE = 1700000000000


def _msgs(node, start, n, payload=b"ct"):
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (start + i) * 1000, 0, node)),
            payload + b"-%d" % (start + i),
        )
        for i in range(n)
    )


def _synced_tree(req: protocol.SyncRequest) -> str:
    """The post-push server tree for `req` — an in-sync client sends
    this, so the response diff is empty and nothing on the serving
    path needs SQLite (the steady-state hot shape)."""
    s = RelayStore()
    try:
        return s.sync(req).merkle_tree
    finally:
        s.close()


def _dump(store):
    """Full store state (every shard's rows + trees) for byte-identity
    asserts. Shard layout is topology, not state — flatten."""
    shards = getattr(store, "shards", None) or [store]
    rows, trees = [], []
    for s in shards:
        rows += [
            (r["userId"], r["timestamp"], r["content"])
            for r in s.db.exec_sql_query(
                'SELECT "timestamp", "userId", "content" FROM "message"'
            )
        ]
        trees += [
            (r["userId"], r["merkleTree"])
            for r in s.db.exec_sql_query(
                'SELECT "userId", "merkleTree" FROM "merkleTree"'
            )
        ]
    return sorted(rows), sorted(trees)


@pytest.fixture
def pair():
    """(write-behind engine, synchronous oracle engine) over fresh
    stores, torn down in order."""
    store = ShardedRelayStore(shards=4)
    wb = WriteBehindQueue(store)
    eng = BatchReconciler(store, write_behind=wb)
    oracle = ShardedRelayStore(shards=4)
    oeng = BatchReconciler(oracle)
    yield store, wb, eng, oracle, oeng
    wb.close()
    eng.close()
    oeng.close()
    store.close()
    oracle.close()


# -- record framing --


def test_record_roundtrip_with_nul_and_unicode():
    ts = _msgs("a" * 16, 0, 3)
    ts_packed = "".join(m.timestamp for m in ts).encode("ascii")
    contents = [b"\x00plain\x00", b"", b"\xff" * 9]
    lens = np.array([len(c) for c in contents], np.int32)
    rec = IngestRecord(
        ["owner-é", "ow2"], [2, 1], ts_packed, b"".join(contents), lens,
        [("owner-é", '{"t": 1}')],
    )
    back = IngestRecord.decode(rec.encode())
    assert back.gu == rec.gu and back.gc == rec.gc
    assert back.ts_packed == rec.ts_packed
    assert back.content_packed == rec.content_packed
    assert back.lens.tolist() == rec.lens.tolist()
    assert back.tree_rows == rec.tree_rows


def test_record_decode_rejects_corruption():
    rec = IngestRecord(
        ["u"], [1], b"x" * 46, b"abc", np.array([3], np.int32), []
    )
    body = rec.encode()
    with pytest.raises(ValueError):
        IngestRecord.decode(body[:-2])
    with pytest.raises(ValueError):
        IngestRecord.decode(body + b"zz")


def test_torn_log_tail_is_discarded(tmp_path):
    rec = IngestRecord(
        ["u"], [1], _msgs("a" * 16, 0, 1)[0].timestamp.encode(), b"abc",
        np.array([3], np.int32), [],
    )
    import struct
    import zlib

    body = rec.encode()
    frame = struct.pack("<I", len(body)) + struct.pack(
        "<I", zlib.crc32(body)
    ) + body
    from evolu_tpu.storage.write_behind import LOG_MAGIC

    good = WriteBehindQueue._decode_log(LOG_MAGIC + frame + frame[: len(frame) // 2])
    assert len(good) == 1  # intact first record; torn tail dropped
    with pytest.raises(ValueError):
        WriteBehindQueue._decode_log(b"not a log" + frame)


# -- serve/drain byte-identity vs the synchronous oracle --


def test_fresh_pushes_and_drained_state_byte_identical(pair):
    store, wb, eng, oracle, oeng = pair
    reqs = [
        protocol.SyncRequest(_msgs("a" * 16, 0, 40), "userA", "a" * 16, "{}"),
        protocol.SyncRequest(_msgs("b" * 16, 0, 23), "userB", "b" * 16, "{}"),
        protocol.SyncRequest((), "userC", "c" * 16, "{}"),  # empty pull
    ]
    assert eng.run_batch_wire(reqs) == oeng.run_batch_wire(reqs)
    wb.flush()
    assert _dump(store) == _dump(oracle)


def test_multi_batch_same_owner_sequential_trees(pair):
    store, wb, eng, oracle, oeng = pair
    for rnd in range(4):
        reqs = [
            protocol.SyncRequest(
                _msgs("a" * 16, rnd * 50, 17), "userA", "a" * 16, "{}"
            )
        ]
        assert eng.run_batch_wire(reqs) == oeng.run_batch_wire(reqs)
    wb.flush()
    assert _dump(store) == _dump(oracle)


def test_cold_sync_waits_on_drain_watermark(pair):
    store, wb, eng, oracle, oeng = pair
    push = [protocol.SyncRequest(_msgs("a" * 16, 0, 30), "uA", "a" * 16, "{}")]
    eng.run_batch_wire(push)
    oeng.run_batch_wire(push)
    # A second node's cold sync needs stored MESSAGES: the respond path
    # must wait for the owner's drain watermark, then serve committed
    # rows — byte-identical to the oracle.
    pull = [protocol.SyncRequest((), "uA", "d" * 16, "{}")]
    got = eng.run_batch_wire(pull)
    want = oeng.run_batch_wire(pull)
    assert got == want
    assert len(got[0]) > 30 * 46  # the rows actually arrived


def test_duplicate_delivery_corrected_exactly_at_drain(pair):
    store, wb, eng, oracle, oeng = pair
    from evolu_tpu.obs import metrics

    before = metrics.get_counter("evolu_wb_corrected_owners_total")
    reqs = [protocol.SyncRequest(_msgs("a" * 16, 0, 12), "uA", "a" * 16, "{}")]
    eng.run_batch_wire(reqs)
    oeng.run_batch_wire(reqs)
    # Client retry: every row is already stored. The optimistic serve
    # tree is transiently imprecise — the DRAIN must repair it exactly.
    eng.run_batch_wire(reqs)
    oeng.run_batch_wire(reqs)
    wb.flush()
    assert _dump(store) == _dump(oracle)
    assert metrics.get_counter("evolu_wb_corrected_owners_total") > before
    # Post-correction traffic re-aligns byte-identically.
    pull = [protocol.SyncRequest((), "uA", "e" * 16, "{}")]
    assert eng.run_batch_wire(pull) == oeng.run_batch_wire(pull)
    fresh = [protocol.SyncRequest(_msgs("a" * 16, 100, 6), "uA", "a" * 16, "{}")]
    assert eng.run_batch_wire(fresh) == oeng.run_batch_wire(fresh)
    wb.flush()
    assert _dump(store) == _dump(oracle)


def test_duplicate_retry_response_tree_is_exact(pair):
    """A duplicate-carrying push (lost-response client retry) must be
    ANSWERED with the drain-corrected exact tree, not the optimistic
    XOR-cancelled one — serving the cancelled tree would make the
    client re-send the row every round, re-cancelling it each time: a
    permanent retry livelock (review finding). With the exact re-read
    the retry's response is byte-identical to the synchronous
    oracle's."""
    store, wb, eng, oracle, oeng = pair
    reqs = [protocol.SyncRequest(_msgs("a" * 16, 0, 9), "uR", "a" * 16, "{}")]
    eng.run_batch_wire(reqs)
    oeng.run_batch_wire(reqs)
    # The retry: every row already stored on both engines.
    assert eng.run_batch_wire(reqs) == oeng.run_batch_wire(reqs)
    wb.flush()
    assert _dump(store) == _dump(oracle)


def test_partial_overlap_batch_converges(pair):
    store, wb, eng, oracle, oeng = pair
    first = [protocol.SyncRequest(_msgs("a" * 16, 0, 10), "uA", "a" * 16, "{}")]
    eng.run_batch_wire(first)
    oeng.run_batch_wire(first)
    # 5 duplicate rows + 5 new ones in one request.
    overlap = [protocol.SyncRequest(_msgs("a" * 16, 5, 10), "uA", "a" * 16, "{}")]
    eng.run_batch_wire(overlap)
    oeng.run_batch_wire(overlap)
    wb.flush()
    assert _dump(store) == _dump(oracle)


def test_non_canonical_case_owner_quarantine_state_identical(pair):
    store, wb, eng, oracle, oeng = pair
    # Canonical width, non-canonical HEX CASE: batchable; the engine
    # quarantines the owner to the host fold. End state must match.
    ts = timestamp_to_string(Timestamp(BASE, 0, "a" * 16)).replace("a", "A")
    reqs = [
        protocol.SyncRequest(
            (protocol.EncryptedCrdtMessage(ts, b"weird"),) + _msgs("b" * 16, 0, 3),
            "uQ", "b" * 16, "{}",
        )
    ]
    assert eng.run_batch_wire(reqs) == oeng.run_batch_wire(reqs)
    wb.flush()
    assert _dump(store) == _dump(oracle)


# -- crash replay --


def test_crash_replay_recovers_acked_writes(tmp_path):
    path = str(tmp_path / "relay.db")
    store = RelayStore(path)
    wb = WriteBehindQueue(store, log_path=path + ".wblog", _drain_delay_s=30.0)
    eng = BatchReconciler(store, write_behind=wb)
    reqs = [protocol.SyncRequest(_msgs("a" * 16, 0, 25), "uA", "a" * 16, "{}")]
    reqs = [protocol.SyncRequest(reqs[0].messages, "uA", "a" * 16,
                                 _synced_tree(reqs[0]))]
    eng.run_batch_wire(reqs)  # ACKed into the log; drain is stalled
    assert wb.backlog()[1] == 25
    # "Crash": abandon the queue without flush/close.
    store.close()
    eng.close()

    store2 = RelayStore(path)
    wb2 = WriteBehindQueue(store2, log_path=path + ".wblog")  # replays
    oracle = RelayStore()
    oeng = BatchReconciler(oracle)
    oeng.run_batch_wire(reqs)
    assert _dump(store2) == _dump(oracle)
    from evolu_tpu.obs import metrics

    assert metrics.get_counter("evolu_wb_replayed_records_total") > 0
    # Replay twice (crash before truncate): idempotent.
    wb2.close()
    store3 = RelayStore(path)
    wb3 = WriteBehindQueue(store3, log_path=path + ".wblog")
    assert _dump(store3) == _dump(oracle)
    wb3.close()
    for s in (store2, store3, oracle):
        s.close()
    oeng.close()


def test_clean_shutdown_leaves_empty_log(tmp_path):
    path = str(tmp_path / "relay.db")
    store = RelayStore(path)
    wb = WriteBehindQueue(store, log_path=path + ".wblog")
    eng = BatchReconciler(store, write_behind=wb)
    eng.run_batch_wire(
        [protocol.SyncRequest(_msgs("a" * 16, 0, 9), "uA", "a" * 16, "{}")]
    )
    wb.close()
    eng.close()
    store.close()
    from evolu_tpu.storage.write_behind import LOG_MAGIC

    with open(path + ".wblog", "rb") as f:
        assert f.read() == LOG_MAGIC  # fully drained + truncated


# -- backpressure --


def test_queue_full_raises_before_any_state_change(pair):
    store, wb, eng, oracle, oeng = pair
    wb.max_rows = 16
    wb._drain_delay_s = 30.0
    base = protocol.SyncRequest(_msgs("a" * 16, 0, 16), "uA", "a" * 16, "{}")
    r1 = [protocol.SyncRequest(base.messages, "uA", "a" * 16, _synced_tree(base))]
    eng.run_batch_wire(r1)
    assert wb.backlog()[1] == 16
    with pytest.raises(WriteBehindFull):
        eng.run_batch_wire(
            [protocol.SyncRequest(_msgs("a" * 16, 100, 8), "uA", "a" * 16, "{}")]
        )
    wb._drain_delay_s = 0.0
    wb.flush(timeout=60)
    # The rejected batch left nothing anywhere: state == oracle of r1.
    oeng.run_batch_wire(r1)
    assert _dump(store) == _dump(oracle)


def test_scheduler_maps_backpressure_to_queue_full():
    from evolu_tpu.server.scheduler import SchedulerQueueFull, SyncScheduler

    store = RelayStore()
    wb = WriteBehindQueue(store, max_rows=8, _drain_delay_s=30.0)
    sched = SyncScheduler(store, write_behind=wb, max_wait_s=0.001)
    try:
        base = protocol.SyncRequest(_msgs("a" * 16, 0, 8), "uA", "a" * 16, "{}")
        sched.submit(
            protocol.SyncRequest(base.messages, "uA", "a" * 16,
                                 _synced_tree(base))
        )
        with pytest.raises(SchedulerQueueFull):
            sched.submit(
                protocol.SyncRequest(_msgs("a" * 16, 50, 4), "uA", "a" * 16, "{}")
            )
    finally:
        wb._drain_delay_s = 0.0
        sched.stop()
        wb.close()
        store.close()


# -- the direct (non-batchable) path barrier --


def test_non_canonical_width_singleton_drains_first():
    """A non-batchable request takes the direct per-request path, which
    must run behind the drain barrier: by the time `sync_wire` touches
    the store, every ACKed row is committed. (A malformed width then
    errors identically to the reference path — on BOTH engines — with
    the store state untouched by the failed transaction.)"""
    from evolu_tpu.core.types import EvoluError
    from evolu_tpu.server.scheduler import SyncScheduler

    store = RelayStore()
    wb = WriteBehindQueue(store, _drain_delay_s=0.2)
    sched = SyncScheduler(store, write_behind=wb, max_wait_s=0.001)
    oracle = RelayStore()
    try:
        base = protocol.SyncRequest(_msgs("a" * 16, 0, 10), "uA", "a" * 16, "{}")
        push = protocol.SyncRequest(base.messages, "uA", "a" * 16,
                                    _synced_tree(base))
        sched.submit(push)
        oracle.sync_wire(push)
        weird = protocol.SyncRequest(
            (protocol.EncryptedCrdtMessage("short-stamp", b"x"),),
            "uA", "a" * 16, "{}",
        )
        with pytest.raises(EvoluError):
            sched.submit(weird)
        with pytest.raises(EvoluError):
            oracle.sync_wire(weird)
        # The barrier drained the ACKed push before the direct path ran.
        assert wb.backlog() == (0, 0)
        assert _dump(store) == _dump(oracle)
    finally:
        sched.stop()
        wb.close()
        store.close()
        oracle.close()


# -- relay surface: env gate, /health, /stats, checkpoint barrier --


def test_relay_env_gate_and_observability(tmp_path, monkeypatch):
    monkeypatch.setenv("EVOLU_WRITE_BEHIND", "1")
    server = RelayServer(ShardedRelayStore(shards=2))
    assert server.write_behind is not None  # env opt-in implies batching
    assert server.scheduler is not None
    server.start()
    try:
        req = protocol.SyncRequest(_msgs("a" * 16, 0, 12), "uZ", "a" * 16, "{}")
        body = protocol.encode_sync_request(req)
        out = urllib.request.urlopen(
            urllib.request.Request(server.url + "/", data=body), timeout=30
        ).read()
        oracle = RelayStore()
        assert out == oracle.sync_wire(req)
        oracle.close()
        h = json.loads(
            urllib.request.urlopen(server.url + "/health", timeout=10).read()
        )
        assert h["write_behind"]["saturated"] is False
        assert h["write_behind"]["last_seq"] >= h["write_behind"]["drained_seq"]
        s = json.loads(
            urllib.request.urlopen(server.url + "/stats", timeout=10).read()
        )
        assert s["write_behind"]["enqueued_rows"] >= 12
    finally:
        server.stop()


def test_health_backlogged_answers_503(monkeypatch):
    server = RelayServer(RelayStore(), write_behind=True)
    server.write_behind.max_rows = 0  # force "saturated"
    server.start()
    try:
        try:
            urllib.request.urlopen(server.url + "/health", timeout=10)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            detail = json.loads(e.read())
            assert detail["status"] == "backlogged"
    finally:
        server.write_behind.max_rows = 1 << 20
        server.stop()


def test_persistent_drain_failure_fails_health(monkeypatch):
    """The drain retries forever (records must not be lost), so a
    PERSISTENT failure must surface through readiness: /health answers
    503 "drain-failing" even though the backlog sits below max_rows —
    otherwise fleet failover keeps routing onto a relay whose
    flush-needing serves all hang (review finding)."""
    import time as _time

    server = RelayServer(RelayStore(), write_behind=True)
    wb = server.write_behind

    def boom(si, ops, exact=False, carry_taint=(), wid=None):
        raise RuntimeError("injected persistent drain failure")

    # The per-shard materialize seam: every drain worker funnels its
    # batches through it, so one patch wedges every shard.
    monkeypatch.setattr(wb, "_materialize_shard", boom)
    server.start()
    try:
        req = protocol.SyncRequest(_msgs("a" * 16, 0, 6), "uF", "a" * 16, "{}")
        base = protocol.SyncRequest(req.messages, "uF", "a" * 16,
                                    _synced_tree(req))
        body = protocol.encode_sync_request(base)
        urllib.request.urlopen(
            urllib.request.Request(server.url + "/", data=body), timeout=30
        ).read()
        deadline = _time.time() + 10
        while _time.time() < deadline and not wb.failing():
            _time.sleep(0.05)
        assert wb.failing()
        try:
            urllib.request.urlopen(server.url + "/health", timeout=10)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["status"] == "drain-failing"
    finally:
        monkeypatch.undo()  # let close() drain the backlog for real
        server.stop()


def test_checkpoint_barrier_sees_drained_state(tmp_path):
    from evolu_tpu.server import snapshot

    path = str(tmp_path / "relay.db")
    store = RelayStore(path)
    wb = WriteBehindQueue(store, log_path=path + ".wblog")
    eng = BatchReconciler(store, write_behind=wb)
    reqs = [protocol.SyncRequest(_msgs("a" * 16, 0, 15), "uA", "a" * 16, "{}")]
    eng.run_batch_wire(reqs)
    ckpt = str(tmp_path / "relay.ckpt")
    snapshot.write_checkpoint(store, ckpt, barrier=wb.drain_barrier)
    # The checkpoint must contain the ACKed-but-async rows: restoring
    # it into a fresh store yields the oracle state.
    restored = RelayStore()
    snapshot.restore_checkpoint(restored, ckpt)
    oracle = RelayStore()
    oeng = BatchReconciler(oracle)
    oeng.run_batch_wire(reqs)
    assert _dump(restored) == _dump(oracle)
    wb.close()
    eng.close()
    oeng.close()
    for s in (store, restored, oracle):
        s.close()


def test_replication_advertises_committed_state_only():
    """A wb relay gossiping to a plain peer: the peer must converge to
    the oracle state (summaries are drained-first, pulls serve
    committed rows)."""
    a = RelayServer(RelayStore(), write_behind=True, peers=[],
                    replication_interval_s=3600).start()
    b = RelayServer(RelayStore(), peers=[a.url],
                    replication_interval_s=3600).start()
    try:
        req = protocol.SyncRequest(_msgs("a" * 16, 0, 20), "uA", "a" * 16, "{}")
        body = protocol.encode_sync_request(req)
        urllib.request.urlopen(
            urllib.request.Request(a.url + "/", data=body), timeout=30
        ).read()
        b.replication.run_once()
        oracle = RelayStore()
        oracle.sync_wire(req)
        assert _dump(b.store) == _dump(oracle)
        oracle.close()
    finally:
        b.stop()
        a.stop()


# -- reset semantics --


def test_reset_drops_pending_and_truncates(tmp_path):
    path = str(tmp_path / "relay.db")
    store = RelayStore(path)
    wb = WriteBehindQueue(store, log_path=path + ".wblog", _drain_delay_s=30.0)
    eng = BatchReconciler(store, write_behind=wb)
    eng.run_batch_wire(
        [protocol.SyncRequest(_msgs("a" * 16, 0, 10), "uA", "a" * 16, "{}")]
    )
    wb._drain_delay_s = 0.0
    wb.reset()
    assert wb.backlog() == (0, 0)
    # flush() returns immediately; a fresh queue over the log replays
    # nothing (truncated).
    wb.flush(timeout=5)
    wb.close()
    wb2 = WriteBehindQueue(store, log_path=path + ".wblog")
    from evolu_tpu.storage.write_behind import LOG_MAGIC

    with open(path + ".wblog", "rb") as f:
        assert f.read() == LOG_MAGIC
    wb2.close()
    eng.close()
    store.close()


# -- PR-19 parallel owner-sharded drain --


def _record_of(owner_msgs):
    """Build an IngestRecord straight from {owner: msgs} (no tree rows
    — the exact/replay path recomputes trees from was-new flags)."""
    gu, gc, ts, ct, lens = [], [], b"", b"", []
    for o, msgs in owner_msgs.items():
        gu.append(o)
        gc.append(len(msgs))
        for m in msgs:
            ts += m.timestamp.encode("ascii")
            ct += m.content
            lens.append(len(m.content))
    return IngestRecord(gu, gc, ts, ct, np.array(lens, np.int32), [])


def _write_log(path, records):
    """Hand-frame a write-behind log (what append_batch's fsync leaves
    on disk) — the crash fixtures build arbitrary pre-crash states
    without racing a real drain."""
    import struct
    import zlib

    from evolu_tpu.storage.write_behind import LOG_MAGIC

    with open(path, "wb") as f:
        f.write(LOG_MAGIC)
        for r in records:
            body = r.encode()
            f.write(struct.pack("<I", len(body)))
            f.write(struct.pack("<I", zlib.crc32(body)))
            f.write(body)


def _owners_per_shard(store, per_shard=1):
    """Deterministic owner names covering every shard of `store`."""
    found = {}
    i = 0
    while any(len(v) < per_shard for v in found.values()) or len(found) < len(store.shards):
        o = f"owner{i}"
        si = store.shard_index(o)
        found.setdefault(si, [])
        if len(found[si]) < per_shard:
            found[si].append(o)
        i += 1
        if i > 10000:
            raise AssertionError("owner search runaway")
    return found


def test_parallel_drain_matches_single_worker_oracle():
    """The tentpole's byte-identity gate: the same workload (multi-
    owner batches + duplicate redelivery, owners on every shard)
    drained by one worker per shard vs ONE worker total lands the
    identical SQLite end state — owners never share rows and LWW
    commutes, so drain concurrency must be unobservable."""
    store = ShardedRelayStore(shards=4)
    wb = WriteBehindQueue(store)  # default: one worker per shard
    eng = BatchReconciler(store, write_behind=wb)
    oracle = ShardedRelayStore(shards=4)
    owb = WriteBehindQueue(oracle, drain_workers=1)
    oeng = BatchReconciler(oracle, write_behind=owb)
    assert wb.drain_workers == 4 and owb.drain_workers == 1

    by_shard = _owners_per_shard(store)
    owners = [os_[0] for os_ in by_shard.values()]
    node = {o: f"{i + 1:016x}" for i, o in enumerate(owners)}
    for rnd in range(3):
        reqs = [
            protocol.SyncRequest(
                _msgs(node[o], rnd * 10, 7 + rnd), o, node[o], "{}"
            )
            for o in owners
        ]
        # Duplicate redelivery of round 0's rows (the retry shape the
        # exact drain correction must converge).
        if rnd == 2:
            reqs += [
                protocol.SyncRequest(_msgs(node[o], 0, 3), o, node[o], "{}")
                for o in owners
            ]
        assert eng.run_batch_wire(reqs) == oeng.run_batch_wire(reqs)
    wb.flush(timeout=60)
    owb.flush(timeout=60)
    assert _dump(store) == _dump(oracle)
    for q, s, e in ((wb, store, eng), (owb, oracle, oeng)):
        q.close()
        e.close()
        s.close()


def test_flush_owner_touches_only_its_shard():
    """A stalled sibling shard must NOT stall flush_owner: the per-
    owner barrier waits on the owner's shard watermark only."""
    import time as _time

    store = ShardedRelayStore(shards=2)
    by_shard = _owners_per_shard(store)
    fast_o, slow_o = by_shard[0][0], by_shard[1][0]
    store2 = ShardedRelayStore(shards=2)
    # Shard 1 (slow_o's shard) sleeps 3s per drain batch; shard 0
    # drains instantly.
    wb = WriteBehindQueue(store2, _shard_delay_s={1: 3.0})
    try:
        wb.append_batch([_record_of({fast_o: _msgs("a" * 16, 0, 5),
                                     slow_o: _msgs("b" * 16, 0, 5)})])
        t0 = _time.monotonic()
        wb.flush_owner(fast_o, timeout=10)
        assert _time.monotonic() - t0 < 2.0  # did not ride the stall
        shards = {s["shard"]: s for s in wb.shard_payloads()}
        assert shards[0]["backlog_rows"] == 0
        assert shards[1]["backlog_rows"] == 5  # sibling still pending
        with pytest.raises(TimeoutError):
            wb.flush(timeout=0.2)  # the composed flush DOES wait
        wb.flush(timeout=30)
    finally:
        wb.close()
        store2.close()
        store.close()


def test_partial_commit_crash_replay_reclassifies_committed_shard(tmp_path):
    """SIGKILL with shard k committed and shard j still pending: the
    log replays BOTH, the end state is byte-identical to the oracle,
    and exactly shard k's rows re-classify as store.duplicate (the
    per-shard retry rule) — with the conservation audit clean."""
    from evolu_tpu.obs import ledger

    by = None
    path = str(tmp_path / "relay.db")
    store = ShardedRelayStore(path, shards=2)
    by = _owners_per_shard(store)
    k_owner, j_owner = by[0][0], by[1][0]
    k_msgs = _msgs("c" * 16, 0, 8)
    j_msgs = _msgs("d" * 16, 0, 6)
    records = [_record_of({k_owner: k_msgs, j_owner: j_msgs})]
    _write_log(path + ".wblog", records)
    # "Pre-crash" state: shard k's transaction committed (rows + tree
    # in SQLite), shard j's did not. Reference mutation, not traffic.
    with ledger.quarantine():
        store.add_messages(k_owner, list(k_msgs))

    ledger.reset()  # the proof window starts at the restart
    wb = WriteBehindQueue(store, log_path=path + ".wblog")  # replays
    oracle = ShardedRelayStore(shards=2)
    with ledger.quarantine():
        oracle.add_messages(k_owner, list(k_msgs))
        oracle.add_messages(j_owner, list(j_msgs))
    assert _dump(store) == _dump(oracle)
    t = ledger.totals()
    assert t.get(ledger.STORE_DUPLICATE, 0) == len(k_msgs)  # exactly k's
    assert t.get(ledger.STORE_INSERTED, 0) == len(j_msgs)
    assert t.get(ledger.INGRESS_REPLAY, 0) == len(k_msgs) + len(j_msgs)
    assert ledger.audit(at_barrier=True) == []
    wb.close()
    store.close()
    oracle.close()


def test_replay_survives_shard_count_change(tmp_path):
    """The log stores owner groups, never shard assignments: a log
    written under shards=2 replays exactly into a shards=3 store
    (re-split by the topology it wakes up under)."""
    owners = [f"owner{i}" for i in range(6)]
    nodes = {o: f"{i + 1:016x}" for i, o in enumerate(owners)}
    records = [
        _record_of({o: _msgs(nodes[o], rnd * 10, 4) for o in owners})
        for rnd in range(2)
    ]
    log_path = str(tmp_path / "wb.wblog")
    _write_log(log_path, records)

    store3 = ShardedRelayStore(str(tmp_path / "relay3.db"), shards=3)
    wb = WriteBehindQueue(store3, log_path=log_path)  # replays under 3
    oracle = ShardedRelayStore(shards=3)
    from evolu_tpu.obs import ledger

    with ledger.quarantine():
        for o in owners:
            for rnd in range(2):
                oracle.add_messages(o, list(_msgs(nodes[o], rnd * 10, 4)))
    assert _dump(store3) == _dump(oracle)
    wb.close()
    store3.close()
    oracle.close()


def test_process_drain_parity(tmp_path):
    """Process-per-shard drain (pure-Python file-backed shards): the
    end state is byte-identical to the synchronous oracle, the mode
    actually engages, and the conservation totals balance (the parent
    posts every terminal from the children's returned counts)."""
    from evolu_tpu.obs import ledger

    path = str(tmp_path / "relay.db")
    store = ShardedRelayStore(path, backend="python", shards=2)
    wb = WriteBehindQueue(store, log_path=path + ".wblog",
                          drain_process=True)
    assert wb.drain_mode == "process"
    eng = BatchReconciler(store, write_behind=wb)
    oracle = ShardedRelayStore(shards=2)
    oeng = BatchReconciler(oracle)
    by = _owners_per_shard(store)
    owners = [os_[0] for os_ in by.values()]
    nodes = {o: f"{i + 1:016x}" for i, o in enumerate(owners)}
    for rnd in range(2):
        reqs = [
            protocol.SyncRequest(_msgs(nodes[o], rnd * 10, 6), o, nodes[o], "{}")
            for o in owners
        ]
        if rnd == 1:  # duplicate redelivery through the child path
            reqs += [
                protocol.SyncRequest(_msgs(nodes[o], 0, 2), o, nodes[o], "{}")
                for o in owners
            ]
        assert eng.run_batch_wire(reqs) == oeng.run_batch_wire(reqs)
    wb.flush(timeout=60)
    assert _dump(store) == _dump(oracle)
    t = ledger.totals()
    assert t.get(ledger.WB_QUEUED, 0) == t.get(ledger.WB_DRAINED, 0)
    wb.close()
    eng.close()
    oeng.close()
    store.close()
    oracle.close()


def test_same_batch_fresh_plus_duplicate_requests_stay_exact():
    """Regression (found while building the sharded-drain parity
    gate, but pre-existing): one batch carrying BOTH a fresh push and
    a duplicate redelivery for the same owner. The record's per-owner
    tree string is the post-batch OPTIMISTIC tree — it pre-folded the
    redelivered rows' hashes (XOR-cancel against the stored copies).
    The old per-op drain landed that string verbatim for the clean op
    and then recomputed the dup op on top of it with zero new rows to
    fold, committing the cancelled (wrong) tree. The per-owner
    regroup in apply_shard_ops recomputes from the STORED tree with
    all of the owner's new rows instead — end state and responses
    must match the synchronous oracle and the reference add_messages
    ground truth."""
    from evolu_tpu.obs import ledger
    from evolu_tpu.server.relay import RelayStore as _RS

    node = "1".zfill(16)
    gt = _RS()
    with ledger.quarantine():
        gt.add_messages("uZ", list(_msgs(node, 0, 6)) + list(_msgs(node, 10, 6)))
    gt_tree = gt.get_merkle_tree_string("uZ")
    gt.close()

    store = ShardedRelayStore(shards=2)
    wb = WriteBehindQueue(store)
    eng = BatchReconciler(store, write_behind=wb)
    oracle = ShardedRelayStore(shards=2)
    oeng = BatchReconciler(oracle)
    r0 = [protocol.SyncRequest(_msgs(node, 0, 6), "uZ", node, "{}")]
    assert eng.run_batch_wire(r0) == oeng.run_batch_wire(r0)
    r1 = [
        protocol.SyncRequest(_msgs(node, 10, 6), "uZ", node, "{}"),
        protocol.SyncRequest(_msgs(node, 0, 2), "uZ", node, "{}"),  # retry
    ]
    assert eng.run_batch_wire(r1) == oeng.run_batch_wire(r1)
    wb.flush(timeout=30)
    assert store.get_merkle_tree_string("uZ") == gt_tree
    assert _dump(store) == _dump(oracle)
    wb.close()
    eng.close()
    oeng.close()
    store.close()
    oracle.close()


def test_process_drain_falls_back_for_memory_or_native_stores():
    """:memory: shards cannot be shared with a child process — the
    queue must fall back to threads, not half-work."""
    store = ShardedRelayStore(shards=2)  # :memory:
    wb = WriteBehindQueue(store, drain_process=True)
    assert wb.drain_mode == "thread"
    wb.close()
    store.close()


def test_stats_and_health_report_per_shard(pair):
    """/stats + /health carry the per-shard split (backlog, watermark
    lag, failure counters) so failover can see WHICH shard is wedged."""
    store, wb, eng, oracle, oeng = pair
    by = _owners_per_shard(store)
    owners = [os_[0] for os_ in by.values()]
    nodes = {o: f"{i + 1:016x}" for i, o in enumerate(owners)}
    eng.run_batch_wire([
        protocol.SyncRequest(_msgs(nodes[o], 0, 4), o, nodes[o], "{}")
        for o in owners
    ])
    wb.flush(timeout=30)
    s = wb.stats_payload()
    assert s["drain_workers"] == 4 and s["drain_mode"] == "thread"
    assert [sh["shard"] for sh in s["shards"]] == [0, 1, 2, 3]
    for sh in s["shards"]:
        assert sh["backlog_rows"] == 0
        assert sh["watermark_lag"] == 0
        assert sh["drain_failures_consecutive"] == 0
        assert sh["failing"] is False
    h = wb.health_payload()
    assert len(h["shards"]) == 4
    assert h["failing"] is False
    from evolu_tpu.obs import metrics

    # The per-shard metrics family posted for at least one shard.
    assert any(
        metrics.get_gauge("evolu_wb_shard_queue_rows", shard=str(si)) == 0
        for si in range(4)
    )


# -- the PR-11 invariant audit (client side: cache is truth) --


def test_winner_cache_verify_against_db():
    from evolu_tpu.ops.winner_cache import DeviceWinnerCache
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.storage.apply import apply_messages
    from evolu_tpu.storage.native import open_database

    db = open_database(":memory:", "auto")
    db.exec(
        'CREATE TABLE IF NOT EXISTS "__message" ('
        '"timestamp" TEXT, "table" TEXT, "row" TEXT, "column" TEXT, '
        '"value" ANY, PRIMARY KEY ("timestamp", "table", "row", "column"))'
    )
    db.exec('CREATE TABLE IF NOT EXISTS "todo" ("id" TEXT PRIMARY KEY, "title" ANY)')
    cache = DeviceWinnerCache(db, adaptive=False)
    msgs = [
        CrdtMessage(
            timestamp_to_string(Timestamp(BASE + i * 1000, 0, "a" * 16)),
            "todo", f"row{i % 7}", "title", f"v{i}",
        )
        for i in range(50)
    ]
    apply_messages(db, {}, msgs, planner=cache.plan_batch)
    assert cache.verify_against_db() == 7  # 7 distinct cells, all exact
    assert cache.verify_against_db(sample=3) == 3
    # Poison one slot host-side: the audit must catch it.
    import jax.numpy as jnp
    import jax

    with jax.enable_x64(True):
        cache._w1 = cache._w1.at[0].set(jnp.uint64(12345))
    with pytest.raises(AssertionError):
        cache.verify_against_db()
    db.close()
